"""graftflow: the shared dataflow core (ISSUE 12) + the call-summary
layer (ISSUE 14).

graftlint's first six rules are per-node pattern matchers; the bug
classes the last five PRs kept fixing by hand — reads of donated
buffers, objects mutated after a thread handoff, acquire-without-
release on error paths — all require tracking a VALUE across
statements. This module owns that machinery once, so the three
dataflow rules (donation-safety, thread-handoff, resource-leak) are
just transfer functions:

  - a statement-ordered CFG walk per function: sequencing is program
    order; `if`/`try`/`match` branches are both executed on copies of
    the state and JOINED conservatively (a fact on either side
    survives); loops run ONE fixpoint pass (body executed twice with a
    join in between — enough to propagate loop-carried facts like "a
    name tainted at the bottom of the body is tainted at the top",
    without iterating to convergence);
  - per-name def-use facts: rules attach a fact to a dotted name
    (`params`, `self.opt_state`) when it is defined or flows somewhere
    interesting, and REASSIGNMENT KILLS it — `params, opt, loss =
    step(params, opt, ...)` launders the name on the same statement
    that donated it, which is why the normal train-loop idiom is clean
    by construction;
  - a lightweight escape lattice: LOCAL (the function owns the value)
    < ALIASED (another local name may refer to the same object) <
    ESCAPED (handed to another thread/queue/executor or stored where
    another thread can see it). Rules consult the lattice instead of
    re-deriving "who else can touch this".

Under-reach policy (the tool's documented design, ARCHITECTURE.md
"Dataflow: taint what escapes, kill on reassign"): whenever the
analysis cannot prove the hazardous flow — an unresolvable call, a
subscripted target, a name rebound through `exec`-level dynamism — it
drops the fact rather than guessing. A dataflow rule that sprays
plausible-but-wrong findings gets suppressed into uselessness; one
that only speaks when the chain is airtight gets fixed.

Summaries (ISSUE 14, "one hop deeper, still never import" —
ARCHITECTURE.md has the design note): `compute_summaries(scan)` runs a
first pass over the whole scan set computing one `Summary` per
function — params that escape / are donated, whether the body performs
a COLLECTIVE EFFECT (lax collectives, shard_map regions,
jax.distributed init, orbax checkpoint save/restore, the async
checkpoint writer's submit/wait), and whether it DRAWS NONDETERMINISM
(wall clock, the unseeded global random/np.random streams, unsorted
os.listdir/glob results, set iteration order, id()/hash()) or returns
a per-host process-identity value. A worklist fixpoint then propagates
the facts along the shared heuristic call graph (core.CallGraph), so
every rule consulting summaries sees one call hop deeper instead of
under-reaching at function boundaries. All facts are MONOTONE finite
sets, so the fixpoint terminates on recursion and call cycles
(tests/graftlint_fixtures/summaries_cycle_fp.py proves it).

Everything here is pure `ast` + stdlib (the graftlint contract: parse,
never import).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import (Any, Callable, Dict, Iterable, Iterator, List,
                    Optional, Set, Tuple)

# ---- escape lattice ----

LOCAL = 0      # only this function's frame can reach the value
ALIASED = 1    # another local name may refer to the same object
ESCAPED = 2    # another thread/queue/executor/shared object can reach it

_LEAF_STMTS = (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr,
               ast.Assert, ast.Delete, ast.Import, ast.ImportFrom,
               ast.Global, ast.Nonlocal, ast.Pass)


# ---- name extraction helpers (the def/use vocabulary) ----

def dotted(node: ast.AST) -> str:
    """'a.b.c' for a Name/Attribute chain, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def is_name_or_prefix(read: str, name: str) -> bool:
    """True when a read of `read` touches the value bound to `name`:
    the name itself or an attribute path under it (`params.shape`
    reads `params`; `self` does not read `self.params`)."""
    return read == name or read.startswith(name + ".")


def bound_names(target: ast.AST) -> List[str]:
    """Dotted names REBOUND by an assignment target (tuple/list/star
    unpacking flattened). Subscript targets (`x[k] = v`) mutate, they
    do not rebind — they are excluded here (see `mutated_bases`)."""
    out: List[str] = []
    stack = [target]
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Starred):
            stack.append(t.value)
        elif isinstance(t, (ast.Name, ast.Attribute)):
            d = dotted(t)
            if d:
                out.append(d)
    return out


def mutated_bases(target: ast.AST) -> List[str]:
    """Dotted base names MUTATED (not rebound) by an assignment
    target: `x[k] = v` and `x.a = v` mutate `x`; plain `x = v` does
    not. For `x.a = v` both the mutation of `x` and the rebind of
    `x.a` are real — callers pick the view they need."""
    out: List[str] = []
    stack = [target]
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Starred):
            stack.append(t.value)
        elif isinstance(t, ast.Subscript):
            d = dotted(t.value)
            if d:
                out.append(d)
        elif isinstance(t, ast.Attribute):
            d = dotted(t.value)
            if d:
                out.append(d)
    return out


def reads(expr: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    """Every dotted name READ inside an expression tree, as (name,
    node). An Attribute chain yields its full dotted path once (the
    rules prefix-match); Store/Del contexts are skipped. Descends into
    lambdas and comprehensions — a closure read is still a read."""
    stack = [expr]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Attribute):
            if isinstance(n.ctx, ast.Load):
                d = dotted(n)
                if d:
                    yield d, n
                    # the chain's names are covered by the prefix
                    # match; don't also yield the inner Name
                    stack.extend(a for a in ast.iter_child_nodes(n)
                                 if not isinstance(a, (ast.Name,
                                                       ast.Attribute)))
                    continue
            stack.append(n.value)
            continue
        if isinstance(n, ast.Name):
            if isinstance(n.ctx, ast.Load):
                yield n.id, n
            continue
        stack.extend(ast.iter_child_nodes(n))


def arg_names(call: ast.Call) -> List[Tuple[Optional[str], str, ast.AST]]:
    """(keyword-or-None, dotted-name, node) for every plain-name
    argument of a call. Complex argument expressions are skipped —
    their values are temporaries no later statement can read
    (under-reach)."""
    out: List[Tuple[Optional[str], str, ast.AST]] = []
    for a in call.args:
        node = a.value if isinstance(a, ast.Starred) else a
        d = dotted(node)
        if d:
            out.append((None, d, node))
    for kw in call.keywords:
        d = dotted(kw.value)
        if d:
            out.append((kw.arg, d, kw.value))
    return out


def stmt_may_raise(stmt: ast.AST) -> bool:
    """Heuristic: a statement containing any call (or an explicit
    raise/assert) can leave the function exceptionally. Attribute and
    subscript reads can too, but flagging those would make every
    statement 'risky' — calls are where the PR-6 leak class actually
    fired."""
    for n in ast.walk(stmt):
        if isinstance(n, (ast.Call, ast.Raise, ast.Assert, ast.Await)):
            return True
    return False


# every compound statement a def can hide inside — a function defined
# in a match-case arm or an async-with body is still a frame to analyze
_CONTAINER_STMTS = (ast.If, ast.Try, ast.With, ast.AsyncWith,
                    ast.For, ast.AsyncFor, ast.While,
                    ast.ExceptHandler) + tuple(
    getattr(ast, n) for n in ("Match", "match_case")
    if hasattr(ast, n))


def iter_functions(tree: ast.AST) -> Iterator[Tuple[ast.AST, str]]:
    """(function-node, enclosing-class-name) for every def in a module,
    including nested ones (each is analyzed as its own frame)."""
    stack: List[Tuple[ast.AST, str]] = [(tree, "")]
    while stack:
        node, cls = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                stack.append((child, child.name))
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                yield child, cls
                stack.append((child, cls))
            elif isinstance(child, _CONTAINER_STMTS):
                stack.append((child, cls))
    return


# ---- the flow engine ----

class FlowVisitor:
    """Transfer-function interface a dataflow rule implements. The
    engine owns control flow (sequencing, branch copies + joins, the
    one-pass loop fixpoint, path death after return/raise/break); the
    visitor owns the state and the findings.

    State objects are opaque to the engine — it only ever calls
    `copy_state` and `join_states`. A `None` state is a dead path
    (after return/raise); `join_states` never sees one."""

    def initial_state(self, fn: ast.AST) -> Any:
        return {}

    def copy_state(self, state: Any) -> Any:
        return dict(state)

    def join_states(self, a: Any, b: Any) -> Any:
        """Conservative branch join: a fact surviving on EITHER side
        survives the join. Default: union, keeping `a`'s fact on
        conflict."""
        out = dict(b)
        out.update(a)
        return out

    # --- hooks the engine calls in execution order ---

    def on_stmt(self, stmt: ast.AST, state: Any) -> None:
        """A leaf statement (Assign/Expr/Return/Raise/...)."""

    def on_expr(self, expr: ast.AST, state: Any) -> None:
        """A control expression evaluated outside a leaf statement:
        an `if`/`while` test, a `for` iterable, a `with` item."""

    def on_bind(self, target: ast.AST, state: Any, source: str,
                value: Optional[ast.AST] = None) -> None:
        """A binding outside a leaf Assign: `for` targets
        (source='for'), `with ... as` (source='with', value=the
        context expr), `except ... as` (source='except'). Default:
        kill facts for the rebound names."""
        for name in bound_names(target):
            state.pop(name, None)

    def on_nested_def(self, node: ast.AST, state: Any) -> None:
        """A nested FunctionDef/AsyncFunctionDef/ClassDef — the engine
        does NOT descend (it runs at call time, in its own frame)."""

    def on_with(self, stmt: ast.AST, state: Any) -> Any:
        """Entering a with-block (after items were evaluated/bound).
        Returns a token passed back to `after_with`."""
        return None

    def after_with(self, token: Any, state: Optional[Any]) -> None:
        pass

    def on_try(self, stmt: ast.Try, state: Any) -> Any:
        """Entering a try. Returns a token passed to `after_try`;
        rules use it to register finally/handler protection."""
        return None

    def after_try(self, token: Any, state: Optional[Any]) -> None:
        pass

    def enter_finally(self) -> None:
        pass

    def exit_finally(self) -> None:
        pass

    def at_exit(self, fn: ast.AST, state: Any) -> None:
        """The implicit return at the end of the body (only reachable
        fall-off paths — a trailing `raise` never gets here)."""


class _LoopCtx:
    __slots__ = ("breaks", "continues")

    def __init__(self):
        self.breaks: List[Any] = []
        self.continues: List[Any] = []


def run_flow(fn: ast.AST, visitor: FlowVisitor) -> None:
    """Drive `visitor` over `fn`'s body in execution order with the
    CFG policy above."""
    state = visitor.initial_state(fn)
    state = _run_body(fn.body, visitor, state, [])
    if state is not None:
        visitor.at_exit(fn, state)


def _join(v: FlowVisitor, a: Any, b: Any) -> Any:
    if a is None:
        return b
    if b is None:
        return a
    return v.join_states(a, b)


def _run_body(body: Iterable[ast.AST], v: FlowVisitor, state: Any,
              loops: List[_LoopCtx]) -> Any:
    for stmt in body:
        if state is None:
            break  # unreachable code: under-reach, don't analyze
        state = _exec(stmt, v, state, loops)
    return state


def _exec(stmt: ast.AST, v: FlowVisitor, state: Any,
          loops: List[_LoopCtx]) -> Any:
    if isinstance(stmt, _LEAF_STMTS):
        v.on_stmt(stmt, state)
        return state

    if isinstance(stmt, ast.Return):
        v.on_stmt(stmt, state)
        return None
    if isinstance(stmt, ast.Raise):
        v.on_stmt(stmt, state)
        return None
    if isinstance(stmt, ast.Break):
        if loops:
            loops[-1].breaks.append(v.copy_state(state))
        return None
    if isinstance(stmt, ast.Continue):
        if loops:
            loops[-1].continues.append(v.copy_state(state))
        return None

    if isinstance(stmt, ast.If):
        v.on_expr(stmt.test, state)
        s_then = _run_body(stmt.body, v, v.copy_state(state), loops)
        s_else = _run_body(stmt.orelse, v, state, loops)
        return _join(v, s_then, s_else)

    if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
        loop = _LoopCtx()
        loops.append(loop)
        try:
            # one fixpoint pass: execute the body twice, joining with
            # the pre-loop state (zero iterations) and the first
            # pass's exit (loop-carried facts) in between
            for _ in range(2):
                if isinstance(stmt, ast.While):
                    v.on_expr(stmt.test, state)
                else:
                    v.on_expr(stmt.iter, state)
                    v.on_bind(stmt.target, state, "for")
                s_body = _run_body(stmt.body, v, v.copy_state(state),
                                   loops)
                for s_cont in loop.continues:
                    s_body = _join(v, s_body, s_cont)
                loop.continues.clear()
                state = _join(v, state, s_body)
        finally:
            loops.pop()
        for s_brk in loop.breaks:
            state = _join(v, state, s_brk)
        if stmt.orelse:
            state = _run_body(stmt.orelse, v, state, loops)
        return state

    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            v.on_expr(item.context_expr, state)
            if item.optional_vars is not None:
                v.on_bind(item.optional_vars, state, "with",
                          value=item.context_expr)
        token = v.on_with(stmt, state)
        state = _run_body(stmt.body, v, state, loops)
        v.after_with(token, state)
        return state

    if isinstance(stmt, ast.Try):
        token = v.on_try(stmt, state)
        entry = v.copy_state(state)
        s_body = _run_body(stmt.body, v, state, loops)
        handler_states = []
        for h in stmt.handlers:
            # an exception can arrive from ANY point in the body: the
            # handler sees the entry state joined with the body-exit
            # state (facts born inside the body may or may not exist)
            hs = _join(v, v.copy_state(entry),
                       None if s_body is None else v.copy_state(s_body))
            if h.name:
                v.on_bind(ast.Name(id=h.name, ctx=ast.Store()), hs,
                          "except")
            handler_states.append(_run_body(h.body, v, hs, loops))
        out = s_body
        if stmt.orelse and out is not None:
            out = _run_body(stmt.orelse, v, out, loops)
        for hs in handler_states:
            out = _join(v, out, hs)
        if stmt.finalbody:
            fin_in = out if out is not None else entry
            v.enter_finally()
            try:
                out = _run_body(stmt.finalbody, v, fin_in, loops)
            finally:
                v.exit_finally()
        v.after_try(token, out)
        return out

    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        v.on_nested_def(stmt, state)
        if isinstance(state, dict):
            state.pop(stmt.name, None)  # the def name is a rebind
        return state

    if hasattr(ast, "Match") and isinstance(stmt, ast.Match):
        v.on_expr(stmt.subject, state)
        out = v.copy_state(state)  # no-match path
        for case in stmt.cases:
            cs = _run_body(case.body, v, v.copy_state(state), loops)
            out = _join(v, out, cs)
        return out

    # anything else (future syntax): treat as an opaque leaf
    v.on_stmt(stmt, state)
    return state


# ====================================================================
# The call-summary layer (ISSUE 14).
# ====================================================================

def call_trailing(call: ast.Call) -> str:
    """Trailing name of a call: foo(...) -> 'foo', a.b.c(...) -> 'c'."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _call_base(call: ast.Call) -> str:
    """Dotted base of an attribute call: a.b.c(...) -> 'a.b'."""
    if isinstance(call.func, ast.Attribute):
        return dotted(call.func.value)
    return ""


# ---- donation vocabulary (shared with rules/donation_safety.py) ----

# the repo's step-factory seams: calling the RESULT donates these
# positional args (training/steps.py, training/sparse_steps.py,
# training/vm_steps.py all funnel through one make_* entry each)
FACTORIES: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {
    "make_train_step": ((0, 1), ()),
    "make_sparse_train_step": ((0, 1), ()),
    "make_vm_train_step": ((0, 1), ()),
}

# assigning from these produces FRESH buffers — immune to alias taint
SNAPSHOT_CALLS = frozenset({"snapshot_state", "copy", "deepcopy",
                            "device_get", "asarray", "array"})

JIT_NAMES = frozenset({"jit", "pjit"})

Spec = Tuple[Tuple[int, ...], Tuple[str, ...]]  # (argnums, argnames)


def _literal_ints(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, int)):
                return None
            out.append(e.value)
        return tuple(out)
    return None


def _literal_strs(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, str)):
                return None
            out.append(e.value)
        return tuple(out)
    return None


def expr_trailing(node: ast.AST) -> str:
    """Trailing name of a Name/Attribute (non-call) expression."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def jit_donate_spec(call: ast.Call) -> Optional[Spec]:
    """The donation spec of a `jit(..., donate_argnums=...)` /
    `functools.partial(jax.jit, donate_argnums=...)` call, or None."""
    name = call_trailing(call)
    if name == "partial":
        if not (call.args and expr_trailing(call.args[0]) in JIT_NAMES):
            return None
    elif name not in JIT_NAMES:
        return None
    argnums: Tuple[int, ...] = ()
    argnames: Tuple[str, ...] = ()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            argnums = _literal_ints(kw.value) or ()
        elif kw.arg == "donate_argnames":
            argnames = _literal_strs(kw.value) or ()
    if argnums or argnames:
        return (argnums, argnames)
    return None


def donating_value_spec(value: ast.AST) -> Optional[Spec]:
    """Spec when `value` evaluates to a donating callable: a
    jit-with-donate call or a step-factory call."""
    if not isinstance(value, ast.Call):
        return None
    spec = jit_donate_spec(value)
    if spec is not None:
        return spec
    if isinstance(value.func, ast.Call):
        # functools.partial(jax.jit, donate_argnums=...)(f)
        spec = jit_donate_spec(value.func)
        if spec is not None:
            return spec
    return FACTORIES.get(call_trailing(value))


class FileDonors:
    """File-level donor tables built in one pre-pass: decorated defs,
    module-scope donor names, and per-class `self.X` donor attrs."""

    def __init__(self, tree: ast.AST):
        self.defs: Dict[str, Spec] = {}
        self.module_names: Dict[str, Spec] = {}
        self.class_attrs: Dict[Tuple[str, str], Spec] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        spec = jit_donate_spec(dec)
                        if spec is not None:
                            self.defs[node.name] = spec
            elif isinstance(node, ast.ClassDef):
                for n in ast.walk(node):
                    if not (isinstance(n, ast.Assign)
                            and isinstance(n.value, ast.Call)):
                        continue
                    spec = donating_value_spec(n.value)
                    if spec is None:
                        continue
                    for t in n.targets:
                        d = dotted(t)
                        if d.startswith("self."):
                            self.class_attrs[(node.name, d)] = spec
        for stmt in getattr(tree, "body", ()):
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Call):
                spec = donating_value_spec(stmt.value)
                if spec is not None:
                    for t in stmt.targets:
                        d = dotted(t)
                        if d:
                            self.module_names[d] = spec


# ---- nondeterminism / effect source vocabulary ----

# VALUE kinds survive any transform; ORDER kinds are killed by
# order-insensitive consumers (sorted/len/sum/min/max/any/all)
ORDER_KINDS = frozenset({"fs-order", "set-order"})

KIND_DESC = {
    "wall-clock": "the wall clock",
    "global-rng": "the unseeded global random stream",
    "fs-order": "unsorted filesystem listing order",
    "set-order": "set iteration order",
    "object-identity": "id()/hash() (PYTHONHASHSEED-dependent, "
                       "differs per process)",
    "process-identity": "a per-host process-identity value",
}

_TIME_FNS = frozenset({"time", "time_ns", "monotonic", "monotonic_ns",
                       "perf_counter", "perf_counter_ns"})
_DATETIME_FNS = frozenset({"now", "utcnow", "today"})
_RANDOM_GLOBAL_FNS = frozenset({
    "random", "randint", "randrange", "choice", "choices", "sample",
    "shuffle", "uniform", "gauss", "normalvariate", "getrandbits",
    "betavariate", "expovariate", "triangular", "vonmisesvariate",
    "paretovariate", "weibullvariate", "lognormvariate"})
_NP_RANDOM_GLOBAL_FNS = frozenset({
    "rand", "randn", "randint", "random", "random_sample", "ranf",
    "choice", "shuffle", "permutation", "uniform", "normal",
    "standard_normal", "binomial", "poisson", "beta", "gamma",
    "exponential", "bytes"})
_NP_ALIASES = frozenset({"np", "numpy", "onp"})
_NP_RANDOM_BASES = frozenset({f"{a}.random" for a in _NP_ALIASES})

# the only calls that provably CARRY iteration order into their
# result — every other call drops ORDER taint (membership/aggregation
# consumers like `sorted`/`len`/`x in s` are order-insensitive, and an
# opaque callee is assumed to be one: under-reach)
_ORDER_MATERIALIZERS = frozenset({"list", "tuple", "iter", "reversed",
                                  "enumerate", "zip", "map", "filter"})

# per-host identity reads: the values that differ across the processes
# of one SPMD program (process_count/device_count are deliberately NOT
# here — they are cohort-uniform)
_PROCESS_IDENTITY_FNS = frozenset({
    "process_index", "host_id", "local_devices", "local_device_count",
    "getpid", "gethostname", "cohort_world"})


def _direct_source(call: ast.Call) -> Optional[Tuple[str, str]]:
    """(kind, description) when `call` directly draws a
    nondeterministic or per-host value; None otherwise."""
    name = call_trailing(call)
    base = _call_base(call)
    if base == "time" and name in _TIME_FNS:
        return ("wall-clock", f"time.{name}()")
    if name in _DATETIME_FNS and base.split(".")[-1] in ("datetime",
                                                         "date"):
        return ("wall-clock", f"{base}.{name}()")
    if base == "random" and name in _RANDOM_GLOBAL_FNS:
        return ("global-rng", f"random.{name}()")
    if base in _NP_RANDOM_BASES and name in _NP_RANDOM_GLOBAL_FNS:
        return ("global-rng", f"{base}.{name}()")
    if (base == "os" and name in ("listdir", "scandir")) \
            or (base == "glob" and name in ("glob", "iglob")):
        return ("fs-order", f"{base}.{name}()")
    if isinstance(call.func, ast.Name) and call.func.id in ("id", "hash") \
            and call.args:
        return ("object-identity", f"{call.func.id}()")
    if isinstance(call.func, ast.Name) \
            and call.func.id in ("set", "frozenset"):
        return ("set-order", f"{call.func.id}(...) iteration order")
    if name in _PROCESS_IDENTITY_FNS:
        return ("process-identity", f"{name}()")
    return None


Taint = Dict[str, Tuple[int, str]]  # kind -> (line, description)


def _merge(into: Taint, frm: Taint) -> None:
    for k, v in frm.items():
        into.setdefault(k, v)


def expr_nondet(expr: Optional[ast.AST], state: Dict[str, Taint],
                call_kinds: Optional[Callable[[ast.Call], Taint]] = None
                ) -> Taint:
    """The taint kinds an expression's VALUE carries: direct sources
    plus reads of tainted names in `state`, with ORDER kinds killed by
    order-insensitive consumers (`sorted(os.listdir(d))` is clean;
    `list(set(x))` is not). `call_kinds` is the interprocedural hook —
    the nondeterminism rule passes a resolver that consults callee
    summaries (`returns_nondet`), the summary pass itself passes None
    (propagation happens in the fixpoint instead)."""
    if expr is None:
        return {}
    if isinstance(expr, ast.Call):
        out: Taint = {}
        for child in ast.iter_child_nodes(expr):
            _merge(out, expr_nondet(child, state, call_kinds))
        src = _direct_source(expr)
        keeps_order = (isinstance(expr.func, ast.Name)
                       and expr.func.id in _ORDER_MATERIALIZERS)
        if src is None and not keeps_order:
            # an opaque callee consuming an ordered value usually does
            # membership/aggregation, which is order-insensitive — only
            # the materializers (list/tuple/...) provably carry the
            # iteration order into their result. VALUE kinds survive
            # any call (float(time.time()) is still the wall clock).
            out = {k: v for k, v in out.items() if k not in ORDER_KINDS}
        if src is not None:
            kind, desc = src
            if kind in ORDER_KINDS:
                # a fresh set's order-taint replaces whatever order
                # taint the argument carried (membership is clean)
                out = {k: v for k, v in out.items()
                       if k not in ORDER_KINDS}
            out.setdefault(kind, (expr.lineno, desc))
        if call_kinds is not None:
            _merge(out, call_kinds(expr) or {})
        return out
    if isinstance(expr, ast.Compare):
        # ==/in/>=-style comparisons read membership, not iteration
        # order: `set(v) >= {"q", "s"}` is deterministic
        out = {}
        for child in ast.iter_child_nodes(expr):
            _merge(out, expr_nondet(child, state, call_kinds))
        return {k: v for k, v in out.items() if k not in ORDER_KINDS}
    if isinstance(expr, ast.Set):
        out = {}
        for child in ast.iter_child_nodes(expr):
            _merge(out, expr_nondet(child, state, call_kinds))
        out = {k: v for k, v in out.items() if k not in ORDER_KINDS}
        out.setdefault("set-order",
                       (expr.lineno, "set display iteration order"))
        return out
    if isinstance(expr, (ast.Name, ast.Attribute)):
        d = dotted(expr)
        out = {}
        if d:
            for name, taint in state.items():
                if is_name_or_prefix(d, name) \
                        or is_name_or_prefix(name, d):
                    _merge(out, taint)
            return out
        # fall through for attribute chains rooted in calls etc.
    out = {}
    if not isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
        for child in ast.iter_child_nodes(expr):
            _merge(out, expr_nondet(child, state, call_kinds))
    return out


# ---- collective-effect vocabulary ----

_LAX_COLLECTIVES = frozenset({
    "psum", "psum_scatter", "pmean", "pmax", "pmin", "ppermute",
    "pshuffle", "all_gather", "all_to_all", "pgather", "all_reduce"})
_MULTIHOST_COLLECTIVES = frozenset({
    "process_allgather", "sync_global_devices", "broadcast_one_to_all",
    "host_local_array_to_global_array",
    "global_array_to_host_local_array"})
_DIST_INIT_NAMES = frozenset({"distributed_initialize",
                              "maybe_initialize"})
_CKPT_NAMED = frozenset({"save_checkpoint", "restore_checkpoint",
                         "load_checkpoint", "release_checkpoint"})
# attribute submit/wait on something that names itself a checkpoint
# writer (`self._ckpt_writer.submit(...)`) — `.submit` alone is generic
# protocol vocabulary the call graph refuses to resolve
_WRITER_HINTS = ("ckpt", "checkpoint", "writer")

# label prefixes: rules key off these (the nondeterminism rule treats
# checkpoint-labelled effects as the "checkpointed state" sink family)
CHECKPOINT_LABEL = "checkpoint save/restore"


def walk_body(node: ast.AST):
    """Walk a def body WITHOUT descending into nested function/class/
    LAMBDA definitions — all separate frames whose bodies run at call
    time, not where they are defined (core.walk_body is the same
    policy minus lambdas; duplicated here so dataflow stays
    core-independent, stricter here because summary EFFECTS must not
    leak out of a merely-defined closure)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def checkpointer_names(fn_node: ast.AST) -> Set[str]:
    """Names with-bound to an orbax-style checkpointer inside this
    function (`with ocp.StandardCheckpointer() as ckptr:`) — calls on
    them are collective checkpoint IO."""
    out: Set[str] = set()
    for n in walk_body(fn_node):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                if (isinstance(item.context_expr, ast.Call)
                        and call_trailing(item.context_expr).endswith(
                            "Checkpointer")
                        and item.optional_vars is not None):
                    d = dotted(item.optional_vars)
                    if d:
                        out.add(d)
    return out


def collective_effect_label(call: ast.Call,
                            ckptr_names: Set[str] = frozenset()
                            ) -> Optional[str]:
    """Label when `call` DIRECTLY performs a collective effect — an
    operation every process of an SPMD cohort must execute in the same
    order or the cohort deadlocks. None otherwise."""
    name = call_trailing(call)
    base = _call_base(call)
    if name in _LAX_COLLECTIVES and (base.endswith("lax") or not base
                                     or base.endswith("jax")):
        return f"collective `{name}`"
    if name in _MULTIHOST_COLLECTIVES:
        return f"collective `{name}`"
    if name == "shard_map":
        return "a shard_map region (its body runs collectives)"
    if name in _DIST_INIT_NAMES or (
            name == "initialize" and "distributed" in base):
        return "jax.distributed init (blocks for the cohort rendezvous)"
    if name in _CKPT_NAMED:
        return f"{CHECKPOINT_LABEL} (`{name}` — a collective orbax IO)"
    if name in ("save", "restore") and base in ckptr_names:
        return f"{CHECKPOINT_LABEL} (orbax `{name}`)"
    if name in ("submit", "wait") and base and any(
            h in base.lower() for h in _WRITER_HINTS):
        return (f"{CHECKPOINT_LABEL} (async checkpoint writer "
                f"`.{name}()` — every process must issue the same "
                "save sequence)")
    return None


# ---- the per-function summary ----

@dataclasses.dataclass
class CallRecord:
    """One resolved call site inside a function body."""
    callee_key: tuple
    callee_qualname: str
    line: int
    in_return: bool                      # the call feeds a return value
    # (call positional index -> CALLER param index) for bare-param args
    arg_params: Tuple[Tuple[int, int], ...] = ()


@dataclasses.dataclass
class Summary:
    """What one function DOES, as visible to callers — computed
    directly from its body, then widened one call hop at a time by the
    `compute_summaries` fixpoint. Every effect entry maps a stable
    label to `(line, via)`: the line is IN THIS FUNCTION (the direct
    site or the call that inherits the effect), `via` is '' for a
    direct site or the callee qualname the effect arrived through."""
    key: tuple
    qualname: str
    path: str
    collective: Dict[str, Tuple[int, str]] = dataclasses.field(
        default_factory=dict)
    nondet: Dict[str, Tuple[int, str]] = dataclasses.field(
        default_factory=dict)
    returns_nondet: Dict[str, Tuple[int, str]] = dataclasses.field(
        default_factory=dict)
    returns_process_identity: bool = False
    escaping_params: Set[str] = dataclasses.field(default_factory=set)
    donated_params: Dict[int, str] = dataclasses.field(
        default_factory=dict)
    calls: List[CallRecord] = dataclasses.field(default_factory=list)


class _ReturnFlow(FlowVisitor):
    """Flow pass powering a Summary's return facts: taints names
    assigned from nondeterministic / per-host expressions, records what
    kinds reach a `return`."""

    def __init__(self):
        self.returns: Taint = {}
        self.returns_pid = False

    def copy_state(self, state):
        return {k: dict(v) for k, v in state.items()}

    def join_states(self, a, b):
        out = {k: dict(v) for k, v in b.items()}
        for name, taint in a.items():
            _merge(out.setdefault(name, {}), taint)
        return out

    def _assign(self, targets, value, state):
        kinds = expr_nondet(value, state)
        names = [d for t in targets for d in bound_names(t)]
        for d in names:
            state.pop(d, None)
        if kinds:
            for d in names:
                state[d] = dict(kinds)
        # mutation through a subscript/attribute store taints the base
        for t in targets:
            for base in mutated_bases(t):
                if kinds:
                    _merge(state.setdefault(base, {}), kinds)

    def on_stmt(self, stmt, state):
        if isinstance(stmt, ast.Assign):
            self._assign(stmt.targets, stmt.value, state)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign([stmt.target], stmt.value, state)
        elif isinstance(stmt, ast.AugAssign):
            kinds = expr_nondet(stmt.value, state)
            for d in bound_names(stmt.target):
                if kinds:
                    _merge(state.setdefault(d, {}), kinds)
        elif isinstance(stmt, ast.Return) and stmt.value is not None:
            kinds = expr_nondet(stmt.value, state)
            for kind, site in kinds.items():
                if kind == "process-identity":
                    self.returns_pid = True
                else:
                    self.returns.setdefault(kind, site)


_ESCAPE_CALL_NAMES = frozenset({"put", "put_nowait", "submit", "send",
                                "Thread", "append"})


def _direct_summary(fn, graph) -> Summary:
    node = fn.node
    s = Summary(key=fn.key, qualname=fn.qualname, path=fn.ctx.rel)
    args = node.args
    params = [a.arg for a in
              list(getattr(args, "posonlyargs", ())) + list(args.args)]
    param_index = {p: i for i, p in enumerate(params)}
    param_set = set(params) - {"self", "cls"}
    donors = _file_donors(fn.ctx)
    ckptrs = checkpointer_names(node)

    return_call_ids = set()
    for n in walk_body(node):
        if isinstance(n, ast.Return) and n.value is not None:
            for c in ast.walk(n.value):
                if isinstance(c, ast.Call):
                    return_call_ids.add(id(c))

    for n in walk_body(node):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            # closure capture of a param is an escape
            for read, _rn in reads(n):
                root = read.split(".", 1)[0]
                if root in param_set:
                    s.escaping_params.add(root)
            continue
        if isinstance(n, ast.Assign):
            # storing a param into an attribute/global publishes it
            for t in n.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    for read, _rn in reads(n.value):
                        root = read.split(".", 1)[0]
                        if root in param_set:
                            s.escaping_params.add(root)
        if not isinstance(n, ast.Call):
            continue
        label = collective_effect_label(n, ckptrs)
        if label is not None:
            s.collective.setdefault(label, (n.lineno, ""))
        src = _direct_source(n)
        if src is not None and src[0] != "process-identity":
            s.nondet.setdefault(src[0], (n.lineno, src[1]))
        if call_trailing(n) in _ESCAPE_CALL_NAMES:
            for a in n.args:
                d = dotted(a)
                if d and d.split(".", 1)[0] in param_set:
                    s.escaping_params.add(d.split(".", 1)[0])
        # donation of a param through a file-local donating callable
        spec = _donor_spec_for_call(n, fn, donors)
        if spec is not None and not fn.cls:
            argnums, argnames = spec
            for i, a in enumerate(n.args):
                d = dotted(a)
                if i in argnums and d in param_index:
                    s.donated_params[param_index[d]] = d
            for kw in n.keywords:
                d = dotted(kw.value)
                if kw.arg in argnames and d in param_index:
                    s.donated_params[param_index[d]] = d
        # resolved call record for the fixpoint
        target = graph.resolve_call(fn, n)
        if target is not None:
            arg_params = []
            shift = 1 if (target.cls
                          and isinstance(n.func, ast.Attribute)) else 0
            for i, a in enumerate(n.args):
                d = dotted(a)
                if d in param_index:
                    arg_params.append((i + shift, param_index[d]))
            s.calls.append(CallRecord(
                callee_key=target.key, callee_qualname=target.qualname,
                line=n.lineno, in_return=id(n) in return_call_ids,
                arg_params=tuple(arg_params)))

    flow = _ReturnFlow()
    run_flow(node, flow)
    s.returns_nondet = flow.returns
    if flow.returns_pid:
        s.returns_process_identity = True
    return s


def _file_donors(ctx) -> FileDonors:
    """One FileDonors per FileContext, cached on the context itself
    (no global table to leak across runs)."""
    d = getattr(ctx, "_graftlint_donors", None)
    if d is None:
        d = FileDonors(ctx.tree)
        ctx._graftlint_donors = d
    return d


def _donor_spec_for_call(call: ast.Call, fn, donors: FileDonors
                         ) -> Optional[Spec]:
    d = dotted(call.func)
    if d:
        if d in donors.defs:
            return donors.defs[d]
        if d in donors.module_names:
            return donors.module_names[d]
        if fn.cls and (fn.cls, d) in donors.class_attrs:
            return donors.class_attrs[(fn.cls, d)]
    if isinstance(call.func, ast.Call):
        return jit_donate_spec(call.func)
    return None


def compute_summaries(scan) -> Dict[tuple, Summary]:
    """Two passes (the module docstring has the contract): direct
    per-function facts, then a worklist fixpoint widening each fact
    one resolved call hop at a time until nothing changes. Monotone —
    labels/kinds only ever get ADDED — so recursion and call cycles
    terminate instead of looping."""
    fns = scan.functions
    graph = scan.graph
    summaries = {fn.key: _direct_summary(fn, graph) for fn in fns}
    changed = True
    while changed:
        changed = False
        for fn in fns:
            s = summaries[fn.key]
            for rec in s.calls:
                cs = summaries.get(rec.callee_key)
                if cs is None or cs is s:
                    continue
                for label in cs.collective:
                    if label not in s.collective:
                        s.collective[label] = (rec.line,
                                               rec.callee_qualname)
                        changed = True
                for kind, site in cs.nondet.items():
                    if kind not in s.nondet:
                        s.nondet[kind] = (rec.line, rec.callee_qualname)
                        changed = True
                if rec.in_return:
                    for kind in cs.returns_nondet:
                        if kind not in s.returns_nondet:
                            s.returns_nondet[kind] = (
                                rec.line, rec.callee_qualname)
                            changed = True
                    if cs.returns_process_identity \
                            and not s.returns_process_identity:
                        s.returns_process_identity = True
                        changed = True
                if not fn.cls and cs.donated_params:
                    for pos, pidx in rec.arg_params:
                        if pos in cs.donated_params \
                                and pidx not in s.donated_params:
                            # the callee donates the buffer our param
                            # aliases — our caller loses it too
                            name = None
                            fargs = fn.node.args
                            plist = [a.arg for a in
                                     list(getattr(fargs, "posonlyargs",
                                                  ())) + list(fargs.args)]
                            if pidx < len(plist):
                                name = plist[pidx]
                            if name:
                                s.donated_params[pidx] = name
                                changed = True
    return summaries
