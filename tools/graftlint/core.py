"""graftlint engine: file loading, suppressions, rule registry, runner.

Design constraints (tools/graftlint/__init__.py has the why):

  - PURE AST: scanned files are parsed, never imported — a lint run can
    not trigger a jax platform init, a TF import, or module-level side
    effects, and a file that fails to import (missing optional dep)
    still gets linted.
  - One parse per file: every rule sees the same `FileContext` (source,
    AST, suppression table), so the whole suite is one O(files) walk.
  - Findings are baseline-matched WITHOUT line numbers (rule + path +
    symbol + message): editing an unrelated part of a file must not
    resurrect a grandfathered finding.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set

# repo root = the directory holding tools/ (pytest.ini, config, README
# all resolve relative to it); rules that need repo-level files take an
# explicit root so fixtures can point them elsewhere.
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the tier-1 scan set (ROADMAP tier-1 runs the suite over exactly this)
DEFAULT_PATHS = ("code2vec_tpu", "tools", "tests")

# never scanned: bytecode, native build trees, and the lint fixtures
# (deliberate true positives — scanning them would fail the repo run)
EXCLUDE_DIRS = frozenset({"__pycache__", "graftlint_fixtures", "build",
                          ".git", ".claude"})

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?P<file>-file)?=(?P<rules>[\w,-]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding. `symbol` is the enclosing def/class qualname
    (baseline stability: line numbers shift, symbols rarely do).
    `detail` is context that may legitimately change when UNRELATED
    code moves (e.g. which hot root first reached a function — BFS
    order); it is rendered but kept OUT of the baseline identity, so
    such drift cannot invalidate grandfathered entries."""

    rule: str
    path: str      # repo-root-relative, posix separators
    line: int
    message: str
    symbol: str = ""
    detail: str = ""

    def key(self) -> tuple:
        """Baseline identity — deliberately line- and detail-free."""
        return (self.rule, self.path, self.symbol, self.message)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        det = f" ({self.detail})" if self.detail else ""
        return (f"{self.path}:{self.line}: {self.rule}{sym}: "
                f"{self.message}{det}")


class FileContext:
    """One parsed source file: AST + the suppression table.

    A `# graftlint: disable=<rules>` comment suppresses matching
    findings on its OWN line and on the NEXT line (so it can trail the
    offending statement or sit on its own line above it);
    `disable-file=` suppresses for the whole file. Rule name `all`
    matches every rule.
    """

    def __init__(self, path: str, root: str = REPO_ROOT):
        self.path = os.path.abspath(path)
        self.root = root
        self.rel = os.path.relpath(self.path, root).replace(os.sep, "/")
        with open(self.path, "r", encoding="utf-8",
                  errors="replace") as f:
            self.source = f.read()
        self.tree = ast.parse(self.source, filename=self.path)
        self.line_suppressed: Dict[int, Set[str]] = {}
        self.file_suppressed: Set[str] = set()
        self._collect_suppressions()

    def _collect_suppressions(self) -> None:
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline)
            comments = [(t.start[0], t.string) for t in tokens
                        if t.type == tokenize.COMMENT]
        except tokenize.TokenError:
            comments = []
        for line, text in comments:
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",")
                     if r.strip()}
            if m.group("file"):
                self.file_suppressed |= rules
            else:
                for ln in (line, line + 1):
                    self.line_suppressed.setdefault(ln, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        for pool in (self.file_suppressed,
                     self.line_suppressed.get(line, ())):
            if rule in pool or "all" in pool:
                return True
        return False


class Rule:
    """One named check. Per-file rules implement `check_file`; rules
    needing the whole scan set (call graphs, cross-file consistency)
    implement `check_repo`; rules consuming the shared function index /
    call graph / summaries (ISSUE 14) implement `check_scan`. A rule
    may implement any combination."""

    name: str = ""
    description: str = ""

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_repo(self, ctxs: Sequence[FileContext],
                   root: str) -> Iterable[Finding]:
        return ()

    def check_scan(self, scan: "Scan") -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator: instantiate + register a Rule by its name."""
    rule = rule_cls()
    assert rule.name and rule.name not in _REGISTRY, rule_cls
    _REGISTRY[rule.name] = rule
    return rule_cls


def _load_rules() -> None:
    if _REGISTRY:
        return
    # importing the package registers every rule module
    import tools.graftlint.rules  # noqa: F401


def all_rules() -> Dict[str, Rule]:
    _load_rules()
    return dict(_REGISTRY)


def get_rule(name: str) -> Rule:
    _load_rules()
    return _REGISTRY[name]


def iter_py_files(paths: Sequence[str], root: str) -> List[str]:
    """Expand files/dirs into a sorted .py file list (excludes
    EXCLUDE_DIRS at any depth)."""
    out: List[str] = []
    for p in paths:
        p = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(p):
            out.append(p)
            continue
        if not os.path.isdir(p):
            # a typo'd path silently scanning zero files would report
            # "clean" (and mark the whole baseline stale) — fail loud
            raise FileNotFoundError(f"graftlint: no such path: {p}")
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in EXCLUDE_DIRS)
            out.extend(os.path.join(dirpath, f)
                       for f in sorted(filenames) if f.endswith(".py"))
    return out


def run_lint(paths: Sequence[str] = DEFAULT_PATHS,
             root: str = REPO_ROOT,
             rules: Optional[Sequence[str]] = None,
             ambiguous_names: frozenset = frozenset()) -> List[Finding]:
    """Parse every file once, run the selected rules, apply inline
    suppressions, return findings sorted by (path, line, rule).
    Baseline filtering is the caller's concern (tools/graftlint/
    baseline.py) — this returns EVERYTHING the rules see.
    `ambiguous_names` (subset scans — the `--changed` gate) blocks
    uniqueness resolution for names the FULL scan set defines more
    than once (CallGraph docstring)."""
    _load_rules()
    selected = [_REGISTRY[r] for r in rules] if rules \
        else list(_REGISTRY.values())
    ctxs: List[FileContext] = []
    findings: List[Finding] = []
    for path in iter_py_files(paths, root):
        try:
            ctxs.append(FileContext(path, root))
        except SyntaxError as e:
            findings.append(Finding(
                rule="parse-error",
                path=os.path.relpath(path, root).replace(os.sep, "/"),
                line=e.lineno or 0,
                message=f"file does not parse: {e.msg}"))
    by_rel = {c.rel: c for c in ctxs}
    scan = Scan(ctxs, root, ambiguous_names)
    for rule in selected:
        for ctx in ctxs:
            findings.extend(rule.check_file(ctx))
        findings.extend(rule.check_repo(ctxs, root))
        findings.extend(rule.check_scan(scan))
    kept = []
    for f in findings:
        ctx = by_rel.get(f.path)
        if ctx is not None and ctx.suppressed(f.rule, f.line):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return kept


# ---- the shared repo view: function index + heuristic call graph ----
#
# Moved here from rules/host_sync.py (ISSUE 14): the summary layer and
# both new rule families need the same index and the same name-heuristic
# resolution, and computing them once per run is what keeps the
# two-pass scan inside the tier-1 wall bound.

@dataclasses.dataclass
class FnInfo:
    """One function definition in the scan set."""
    ctx: FileContext
    node: ast.AST           # FunctionDef / AsyncFunctionDef
    cls: str                # enclosing class name ('' at module level)
    scope: str = ""         # enclosing DEF chain ('' unless nested in
    #                         a function: 'outer' / 'outer.inner') —
    #                         keeps a nested def from colliding with a
    #                         same-named module-level def in key/
    #                         resolution (they are different functions
    #                         with different summaries)

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name

    @property
    def key(self):
        return (self.ctx.rel, self.cls, self.scope, self.name)


def index_functions(ctxs: Sequence[FileContext]) -> List[FnInfo]:
    """Every def in the scan set, including ones nested in other defs
    and inside compound statements (loop bodies, except-import
    fallbacks, match arms)."""
    fns: List[FnInfo] = []
    for ctx in ctxs:
        stack = [(ctx.tree, "", "")]
        while stack:
            node, cls, scope = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    stack.append((child, child.name, scope))
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    fns.append(FnInfo(ctx, child, cls, scope))
                    inner = f"{scope}.{child.name}" if scope \
                        else child.name
                    stack.append((child, cls, inner))
                elif isinstance(child, _CONTAINER_STMT_TYPES):
                    stack.append((child, cls, scope))
    return fns


_CONTAINER_STMT_TYPES = (ast.If, ast.Try, ast.With, ast.AsyncWith,
                         ast.For, ast.AsyncFor, ast.While,
                         ast.ExceptHandler) + tuple(
    getattr(ast, n) for n in ("Match", "match_case") if hasattr(ast, n))

# attribute-call names too generic to resolve by global uniqueness
# (container/protocol vocabulary — resolving `.get()` to some class's
# `get` would build fantasy edges)
GENERIC_ATTRS = frozenset({
    "get", "put", "items", "keys", "values", "append", "add", "update",
    "pop", "close", "open", "read", "write", "run", "start", "stop",
    "join", "split", "copy", "clear", "count", "index", "sort", "submit",
})


class CallGraph:
    """Name-heuristic call graph over the indexed functions. Resolution
    policy (under-reach by design — rules/host_sync.py docstring has
    the rationale): simple names resolve within the module then to a
    globally-unique def; `self.x(...)` resolves within the class; other
    attribute calls resolve only when the method name is defined
    exactly once repo-wide and is not a GENERIC_ATTRS protocol name.

    `ambiguous_names` blocks uniqueness resolution for names known to
    be multiply-defined OUTSIDE this scan set: a `--changed` subset
    scan would otherwise resolve a name the full scan leaves ambiguous
    (the other definition's file not being in the subset), producing
    phantom findings tier-1 never emits."""

    def __init__(self, fns: List[FnInfo],
                 ambiguous_names: frozenset = frozenset()):
        self.fns = fns
        self.ambiguous = ambiguous_names
        self.by_key = {f.key: f for f in fns}
        # GLOBAL resolution tables hold only ADDRESSABLE defs: a def
        # nested inside another function (f.scope) is not importable/
        # callable from outside its frame, so letting it shadow (or be
        # merged with) a same-named module-level def would corrupt
        # both the summaries and the uniqueness resolution. Nested
        # defs resolve LEXICALLY instead (self.scoped): callable from
        # within their enclosing frame's scope chain only — hot
        # functions keep their reach into nested helpers.
        self.by_name: Dict[str, List[FnInfo]] = {}
        self.methods: Dict[tuple, Dict[str, FnInfo]] = {}
        self.module_fns: Dict[str, Dict[str, FnInfo]] = {}
        self.scoped: Dict[tuple, Dict[str, FnInfo]] = {}
        for f in fns:
            if f.scope:
                self.scoped.setdefault(
                    (f.ctx.rel, f.cls, f.scope), {})[f.name] = f
                continue
            self.by_name.setdefault(f.name, []).append(f)
            if f.cls:
                self.methods.setdefault(
                    (f.ctx.rel, f.cls), {})[f.name] = f
            else:
                self.module_fns.setdefault(f.ctx.rel, {})[f.name] = f

    def _unique(self, name: str) -> Optional[FnInfo]:
        if name in self.ambiguous:
            return None
        hits = self.by_name.get(name, ())
        return hits[0] if len(hits) == 1 else None

    def resolve_call(self, fn: FnInfo, call: ast.Call) -> Optional[FnInfo]:
        func = call.func
        if isinstance(func, ast.Name):
            # lexical chain first: defs nested in THIS frame, then in
            # each enclosing frame (Python name resolution order —
            # locals, enclosing, module)
            frame = f"{fn.scope}.{fn.name}" if fn.scope else fn.name
            while frame:
                hit = self.scoped.get(
                    (fn.ctx.rel, fn.cls, frame), {}).get(func.id)
                if hit is not None:
                    return hit
                frame = frame.rpartition(".")[0]
            local = self.module_fns.get(fn.ctx.rel, {}).get(func.id)
            if local is not None:
                return local
            return self._unique(func.id)  # imported def elsewhere
        if isinstance(func, ast.Attribute):
            attr = func.attr
            if is_self_attr(func) is not None and fn.cls:
                mine = self.methods.get((fn.ctx.rel, fn.cls), {}).get(attr)
                if mine is not None:
                    return mine
            if attr in GENERIC_ATTRS:
                return None
            return self._unique(attr)
        return None

    def callees(self, fn: FnInfo) -> Iterable[FnInfo]:
        for node in walk_body(fn.node):
            if isinstance(node, ast.Call):
                target = self.resolve_call(fn, node)
                if target is not None:
                    yield target


class Scan:
    """One lint run's shared repo view. Built once per `run_lint` and
    handed to every `check_scan` rule; the function index, call graph
    and per-function summaries (tools/graftlint/dataflow.py) are all
    computed LAZILY — a rule-scoped run that never touches them pays
    nothing."""

    def __init__(self, ctxs: Sequence[FileContext], root: str,
                 ambiguous_names: frozenset = frozenset()):
        self.ctxs = list(ctxs)
        self.root = root
        self.ambiguous_names = ambiguous_names
        self._functions: Optional[List[FnInfo]] = None
        self._graph: Optional[CallGraph] = None
        self._summaries = None

    @property
    def functions(self) -> List[FnInfo]:
        if self._functions is None:
            self._functions = index_functions(self.ctxs)
        return self._functions

    @property
    def graph(self) -> CallGraph:
        if self._graph is None:
            self._graph = CallGraph(self.functions,
                                    self.ambiguous_names)
        return self._graph

    @property
    def summaries(self):
        """{fn.key: dataflow.Summary} after interprocedural
        propagation."""
        if self._summaries is None:
            from tools.graftlint import dataflow
            self._summaries = dataflow.compute_summaries(self)
        return self._summaries


# ---- shared AST helpers (used by several rules) ----

def call_name(node: ast.Call) -> str:
    """Trailing name of a call: foo(...) -> 'foo', a.b.c(...) -> 'c'."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for a Name/Attribute chain, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def is_self_attr(node: ast.AST) -> Optional[str]:
    """'x' when node is `self.x`, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def walk_body(node: ast.AST, *, into_defs: bool = False):
    """Walk a def/class body WITHOUT descending into nested function /
    class definitions (they are separate symbols with their own
    reachability / lock context)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not into_defs and isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))
