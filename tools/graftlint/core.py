"""graftlint engine: file loading, suppressions, rule registry, runner.

Design constraints (tools/graftlint/__init__.py has the why):

  - PURE AST: scanned files are parsed, never imported — a lint run can
    not trigger a jax platform init, a TF import, or module-level side
    effects, and a file that fails to import (missing optional dep)
    still gets linted.
  - One parse per file: every rule sees the same `FileContext` (source,
    AST, suppression table), so the whole suite is one O(files) walk.
  - Findings are baseline-matched WITHOUT line numbers (rule + path +
    symbol + message): editing an unrelated part of a file must not
    resurrect a grandfathered finding.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set

# repo root = the directory holding tools/ (pytest.ini, config, README
# all resolve relative to it); rules that need repo-level files take an
# explicit root so fixtures can point them elsewhere.
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the tier-1 scan set (ROADMAP tier-1 runs the suite over exactly this)
DEFAULT_PATHS = ("code2vec_tpu", "tools", "tests")

# never scanned: bytecode, native build trees, and the lint fixtures
# (deliberate true positives — scanning them would fail the repo run)
EXCLUDE_DIRS = frozenset({"__pycache__", "graftlint_fixtures", "build",
                          ".git", ".claude"})

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?P<file>-file)?=(?P<rules>[\w,-]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding. `symbol` is the enclosing def/class qualname
    (baseline stability: line numbers shift, symbols rarely do).
    `detail` is context that may legitimately change when UNRELATED
    code moves (e.g. which hot root first reached a function — BFS
    order); it is rendered but kept OUT of the baseline identity, so
    such drift cannot invalidate grandfathered entries."""

    rule: str
    path: str      # repo-root-relative, posix separators
    line: int
    message: str
    symbol: str = ""
    detail: str = ""

    def key(self) -> tuple:
        """Baseline identity — deliberately line- and detail-free."""
        return (self.rule, self.path, self.symbol, self.message)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        det = f" ({self.detail})" if self.detail else ""
        return (f"{self.path}:{self.line}: {self.rule}{sym}: "
                f"{self.message}{det}")


class FileContext:
    """One parsed source file: AST + the suppression table.

    A `# graftlint: disable=<rules>` comment suppresses matching
    findings on its OWN line and on the NEXT line (so it can trail the
    offending statement or sit on its own line above it);
    `disable-file=` suppresses for the whole file. Rule name `all`
    matches every rule.
    """

    def __init__(self, path: str, root: str = REPO_ROOT):
        self.path = os.path.abspath(path)
        self.root = root
        self.rel = os.path.relpath(self.path, root).replace(os.sep, "/")
        with open(self.path, "r", encoding="utf-8",
                  errors="replace") as f:
            self.source = f.read()
        self.tree = ast.parse(self.source, filename=self.path)
        self.line_suppressed: Dict[int, Set[str]] = {}
        self.file_suppressed: Set[str] = set()
        self._collect_suppressions()

    def _collect_suppressions(self) -> None:
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline)
            comments = [(t.start[0], t.string) for t in tokens
                        if t.type == tokenize.COMMENT]
        except tokenize.TokenError:
            comments = []
        for line, text in comments:
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",")
                     if r.strip()}
            if m.group("file"):
                self.file_suppressed |= rules
            else:
                for ln in (line, line + 1):
                    self.line_suppressed.setdefault(ln, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        for pool in (self.file_suppressed,
                     self.line_suppressed.get(line, ())):
            if rule in pool or "all" in pool:
                return True
        return False


class Rule:
    """One named check. Per-file rules implement `check_file`; rules
    needing the whole scan set (call graphs, cross-file consistency)
    implement `check_repo`. A rule may implement both."""

    name: str = ""
    description: str = ""

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_repo(self, ctxs: Sequence[FileContext],
                   root: str) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator: instantiate + register a Rule by its name."""
    rule = rule_cls()
    assert rule.name and rule.name not in _REGISTRY, rule_cls
    _REGISTRY[rule.name] = rule
    return rule_cls


def _load_rules() -> None:
    if _REGISTRY:
        return
    # importing the package registers every rule module
    import tools.graftlint.rules  # noqa: F401


def all_rules() -> Dict[str, Rule]:
    _load_rules()
    return dict(_REGISTRY)


def get_rule(name: str) -> Rule:
    _load_rules()
    return _REGISTRY[name]


def iter_py_files(paths: Sequence[str], root: str) -> List[str]:
    """Expand files/dirs into a sorted .py file list (excludes
    EXCLUDE_DIRS at any depth)."""
    out: List[str] = []
    for p in paths:
        p = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(p):
            out.append(p)
            continue
        if not os.path.isdir(p):
            # a typo'd path silently scanning zero files would report
            # "clean" (and mark the whole baseline stale) — fail loud
            raise FileNotFoundError(f"graftlint: no such path: {p}")
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in EXCLUDE_DIRS)
            out.extend(os.path.join(dirpath, f)
                       for f in sorted(filenames) if f.endswith(".py"))
    return out


def run_lint(paths: Sequence[str] = DEFAULT_PATHS,
             root: str = REPO_ROOT,
             rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Parse every file once, run the selected rules, apply inline
    suppressions, return findings sorted by (path, line, rule).
    Baseline filtering is the caller's concern (tools/graftlint/
    baseline.py) — this returns EVERYTHING the rules see."""
    _load_rules()
    selected = [_REGISTRY[r] for r in rules] if rules \
        else list(_REGISTRY.values())
    ctxs: List[FileContext] = []
    findings: List[Finding] = []
    for path in iter_py_files(paths, root):
        try:
            ctxs.append(FileContext(path, root))
        except SyntaxError as e:
            findings.append(Finding(
                rule="parse-error",
                path=os.path.relpath(path, root).replace(os.sep, "/"),
                line=e.lineno or 0,
                message=f"file does not parse: {e.msg}"))
    by_rel = {c.rel: c for c in ctxs}
    for rule in selected:
        for ctx in ctxs:
            findings.extend(rule.check_file(ctx))
        findings.extend(rule.check_repo(ctxs, root))
    kept = []
    for f in findings:
        ctx = by_rel.get(f.path)
        if ctx is not None and ctx.suppressed(f.rule, f.line):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return kept


# ---- shared AST helpers (used by several rules) ----

def call_name(node: ast.Call) -> str:
    """Trailing name of a call: foo(...) -> 'foo', a.b.c(...) -> 'c'."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for a Name/Attribute chain, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def is_self_attr(node: ast.AST) -> Optional[str]:
    """'x' when node is `self.x`, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def walk_body(node: ast.AST, *, into_defs: bool = False):
    """Walk a def/class body WITHOUT descending into nested function /
    class definitions (they are separate symbols with their own
    reachability / lock context)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not into_defs and isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))
