#!/usr/bin/env python3
"""Load generator for the batched serving subsystem (ISSUE 3).

Replays extractor-format requests against `serving/server.py` and
reports p50/p95/p99 latency + throughput through the obs registry —
the serving analogue of bench.py's training numbers.

Modes:
  - closed  — `--concurrency` workers, each issuing its next request the
              moment the previous one returns (throughput-bound).
  - open    — requests ARRIVE at `--qps` regardless of completions
              (Poisson-less fixed-interval arrivals); overload shows up
              as shed requests, not as a slowed generator.
  - sequential — the pre-server baseline: one `model.predict` at a time
              on one thread (what the REPL alone could drive).
  - compare — sequential then closed on the same corpus; prints the
              throughput ratio (the ISSUE 3 acceptance metric).

A corpus is one request per line-group: `--corpus <file.c2v>` (raw
extractor/preprocess lines, grouped `--methods` per request) or the
built-in synthetic generator. `--load <ckpt>` serves a real model;
`--synthetic` builds a tiny random-weight model in a temp dir (latency
is shape-, not value-dependent — fine for load testing).

Long-run mode (`--duration S`) loops the corpus for S seconds — pytest
runs it `slow`-marked only (tests/test_loadgen.py).

Reports go to stdout as JSON; with `--telemetry_dir` the run also lands
as a JSONL event log (`kind: loadgen`) that tools/telemetry_report.py
renders into the BASELINE.md serving row.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# mirrors tests/helpers.make_raw_lines' shape but stays standalone:
# tools must not import the test tree
_TOKENS = ["foo", "bar", "baz", "qux", "value", "name", "index", "count"]
_PATHS = [str(h) for h in (123456, -98765, 424242, 1337, -777, 31415)]
_TARGETS = ["get|value", "set|value", "get|name", "set|name", "add|item",
            "remove|item", "to|string", "is|empty"]


def gen_corpus(n_requests: int, methods_per_request: int = 1,
               max_ctx: int = 12, seed: int = 0,
               distinct: bool = True) -> List[List[str]]:
    """Synthetic extractor-format requests. `distinct=True` salts every
    method's token choice with its global index so an LRU cache can't
    turn a throughput run into a cache benchmark."""
    rng = random.Random(seed)
    corpus = []
    for r in range(n_requests):
        lines = []
        for m in range(methods_per_request):
            uid = r * methods_per_request + m
            t_idx = rng.randrange(len(_TARGETS))
            ctxs = []
            for c in range(rng.randint(2, max_ctx)):
                tok_a = _TOKENS[(t_idx + c) % len(_TOKENS)]
                tok_b = (f"u{uid}" if distinct and c == 0
                         else _TOKENS[(t_idx * 3 + c) % len(_TOKENS)])
                ctxs.append(f"{tok_a},{rng.choice(_PATHS)},{tok_b}")
            lines.append(_TARGETS[t_idx] + " " + " ".join(ctxs))
        corpus.append(lines)
    return corpus


def _percentiles(stat) -> Dict[str, float]:
    s = stat.summary()
    return {k: s[k] for k in ("count", "mean_ms", "p50_ms", "p95_ms",
                              "p99_ms", "max_ms")}


def run_sequential(model, corpus: List[List[str]],
                   duration: Optional[float] = None) -> Dict:
    """Baseline: one request at a time through `model.predict` — the
    pre-server path (extract cost excluded on both sides)."""
    from code2vec_tpu.obs import Telemetry
    tele = Telemetry.memory("loadgen-seq")
    t_start = time.perf_counter()
    done = 0
    i = 0
    while True:
        if duration is None:
            if i >= len(corpus):
                break
        elif time.perf_counter() - t_start >= duration:
            break
        t0 = time.perf_counter()
        model.predict(corpus[i % len(corpus)])
        tele.record_ms("loadgen/request_ms",
                       (time.perf_counter() - t0) * 1e3)
        done += 1
        i += 1
    wall = time.perf_counter() - t_start
    return {"mode": "sequential", "requests": done, "ok": done,
            "shed": 0, "errors": 0, "wall_s": round(wall, 3),
            "throughput_rps": round(done / max(wall, 1e-9), 2),
            "latency": _percentiles(tele.timer("loadgen/request_ms"))}


def _modulation_fn(modulation: Optional[str], period_s: float):
    """Offered-load multiplier over elapsed time (ISSUE 18: the open
    loop as a traffic MODEL, not a metronome):

      - None      — flat 1.0 (the PR-3 behavior);
      - "diurnal" — a smooth day-cycle compressed to `period_s`:
                    1 + 0.5*sin(2*pi*t/period), floored at 0.05 so the
                    trough still trickles;
      - "bursty"  — a 3x spike for the first 10% of each period, 0.8x
                    the rest: the flash-crowd shape autoscaling and
                    admission control have to absorb.
    """
    if modulation is None or modulation == "none":
        return lambda _t: 1.0
    if modulation == "diurnal":
        import math
        return lambda t: max(
            0.05, 1.0 + 0.5 * math.sin(2 * math.pi * t / period_s))
    if modulation == "bursty":
        return lambda t: 3.0 if (t % period_s) < 0.1 * period_s else 0.8
    raise ValueError(f"unknown modulation {modulation!r}")


def run_load(server, corpus: List[List[str]], mode: str = "closed",
             concurrency: int = 8, qps: float = 100.0,
             duration: Optional[float] = None,
             arrivals: str = "fixed",
             modulation: Optional[str] = None,
             modulation_period_s: float = 60.0,
             hot_key_frac: float = 0.0, hot_keys: int = 8,
             seed: int = 0) -> Dict:
    """Drive `server.predict_lines` with the chosen arrival process.
    The server must be started (buckets warmed) by the caller.

    Open-loop extras (ISSUE 18): `arrivals="poisson"` draws
    exponential inter-arrival gaps (the memoryless process real
    traffic approximates — fixed intervals can phase-lock with the
    batcher window and hide tail latency); `modulation` shapes the
    instantaneous rate (see `_modulation_fn`); `hot_key_frac` sends
    that fraction of arrivals to the first `hot_keys` corpus entries
    (Zipf-style skew — what makes the shared prediction cache earn
    its keep under replica fan-out). All draws come from one seeded
    stream, so a capture is replayable."""
    from code2vec_tpu.serving.batcher import ServerOverloaded

    tele = server.telemetry
    lock = threading.Lock()
    state = {"next": 0, "ok": 0, "shed": 0, "errors": 0}
    t_start = time.perf_counter()

    def _expired() -> bool:
        return (duration is not None
                and time.perf_counter() - t_start >= duration)

    def one(i: int) -> None:
        t0 = time.perf_counter()
        try:
            server.predict_lines(corpus[i % len(corpus)])
            with lock:
                state["ok"] += 1
            tele.record_ms("loadgen/request_ms",
                           (time.perf_counter() - t0) * 1e3)
        except ServerOverloaded:
            with lock:
                state["shed"] += 1
        except Exception as e:  # noqa: BLE001 — counted + sampled,
            with lock:          # reported, not fatal
                state["errors"] += 1
                state.setdefault("first_error", repr(e))

    if mode == "closed":
        def worker():
            while True:
                with lock:
                    i = state["next"]
                    if _expired() or (duration is None
                                      and i >= len(corpus)):
                        return
                    state["next"] = i + 1
                one(i)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    elif mode == "open":
        import concurrent.futures
        if arrivals not in ("fixed", "poisson"):
            raise ValueError(f"unknown arrivals {arrivals!r}")
        rng = random.Random(seed)
        mod_fn = _modulation_fn(modulation, modulation_period_s)
        n_hot = max(1, min(hot_keys, len(corpus)))
        n = len(corpus) if duration is None else (1 << 30)
        next_arrival = t_start
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=concurrency) as pool:
            futures = []
            for i in range(n):
                if _expired():
                    break
                idx = i
                if hot_key_frac > 0 and rng.random() < hot_key_frac:
                    # skewed traffic: this arrival re-asks one of the
                    # hot keys instead of walking the corpus
                    idx = rng.randrange(n_hot)
                futures.append(pool.submit(one, idx))
                if len(futures) >= 4096:
                    # long-run soak mode: reap finished futures so the
                    # list stays bounded over hours of offered load
                    futures = [f for f in futures if not f.done()]
                # instantaneous rate at THIS arrival; the gap to the
                # next one is 1/rate (fixed) or an exponential draw
                # with that mean (poisson)
                rate = max(1e-9, qps * mod_fn(next_arrival - t_start))
                gap = (rng.expovariate(rate) if arrivals == "poisson"
                       else 1.0 / rate)
                next_arrival += gap
                sleep = next_arrival - time.perf_counter()
                if sleep > 0:
                    time.sleep(sleep)
            for f in futures:
                f.result()
    else:
        raise ValueError(f"unknown mode {mode!r}")

    wall = time.perf_counter() - t_start
    issued = state["ok"] + state["shed"] + state["errors"]
    report = {
        "mode": mode, "concurrency": concurrency,
        "requests": issued, "ok": state["ok"], "shed": state["shed"],
        "errors": state["errors"], "wall_s": round(wall, 3),
        "throughput_rps": round(state["ok"] / max(wall, 1e-9), 2),
        "latency": _percentiles(tele.timer("loadgen/request_ms")),
        "counters": dict(tele.counters),
    }
    if state["errors"]:
        report["first_error"] = state["first_error"]
    if mode == "open":
        report["offered_qps"] = qps
        report["arrivals"] = arrivals
        report["modulation"] = modulation or "none"
        if modulation:
            report["modulation_period_s"] = modulation_period_s
        if hot_key_frac > 0:
            report["hot_key_frac"] = hot_key_frac
            report["hot_keys"] = hot_keys
    return report


def _build_model(args):
    from code2vec_tpu.config import Config
    from code2vec_tpu.models.jax_model import Code2VecModel
    if args.load:
        cfg = Config()
        cfg.load_path = args.load
    else:  # --synthetic: tiny random-weight model in a temp workdir
        from code2vec_tpu.data import preprocess as preprocess_mod
        workdir = tempfile.mkdtemp(prefix="loadgen_")
        raw = os.path.join(workdir, "raw.txt")
        flat = [ln for req in gen_corpus(64, 2, seed=7) for ln in req]
        with open(raw, "w", encoding="utf-8") as f:
            f.write("\n".join(flat) + "\n")
        prefix = os.path.join(workdir, "tiny")
        preprocess_mod.main([
            "--train_data", raw, "--val_data", raw, "--test_data", raw,
            "--max_contexts", "16", "--word_vocab_size", "1000",
            "--path_vocab_size", "1000", "--target_vocab_size", "1000",
            "--output_name", prefix])
        cfg = Config(MAX_CONTEXTS=16, MAX_TOKEN_VOCAB_SIZE=1000,
                     MAX_PATH_VOCAB_SIZE=1000,
                     MAX_TARGET_VOCAB_SIZE=1000,
                     DEFAULT_EMBEDDINGS_SIZE=16, USE_BF16=False)
        cfg.train_data_path = prefix
    for name in ("serve_batch_max", "serve_batch_timeout_ms",
                 "serve_queue_depth", "serve_deadline_ms",
                 "serve_cache_size"):
        val = getattr(args, name)
        if val is not None:
            setattr(cfg, name.upper(), val)
    return cfg, Code2VecModel(cfg)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", default="compare",
                    choices=["closed", "open", "sequential", "compare"])
    ap.add_argument("--load", default=None,
                    help="checkpoint dir; omit for --synthetic")
    ap.add_argument("--synthetic", action="store_true",
                    help="tiny random-weight model (default when no "
                         "--load)")
    ap.add_argument("--corpus", default=None,
                    help="file of raw extractor lines; default: "
                         "synthetic corpus")
    ap.add_argument("--requests", type=int, default=128)
    ap.add_argument("--methods", type=int, default=1,
                    help="methods per request")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--qps", type=float, default=100.0,
                    help="open-loop offered load")
    ap.add_argument("--arrivals", default="fixed",
                    choices=["fixed", "poisson"],
                    help="open-loop arrival process: fixed intervals "
                         "or Poisson (exponential gaps)")
    ap.add_argument("--modulation", default="none",
                    choices=["none", "diurnal", "bursty"],
                    help="open-loop rate shaping: a compressed "
                         "day-cycle sine or a 3x flash-crowd burst "
                         "per period")
    ap.add_argument("--modulation_period_s", type=float, default=60.0,
                    help="one diurnal/bursty cycle length in seconds")
    ap.add_argument("--hot_key_frac", type=float, default=0.0,
                    help="fraction of open-loop arrivals redirected "
                         "to the --hot_keys hottest corpus entries "
                         "(cache-skew traffic)")
    ap.add_argument("--hot_keys", type=int, default=8,
                    help="size of the hot-key set")
    ap.add_argument("--seed", type=int, default=0,
                    help="arrival/hot-key draw seed (replayable "
                         "captures)")
    ap.add_argument("--duration", type=float, default=None,
                    help="long-run mode: loop the corpus for S seconds")
    ap.add_argument("--serve_batch_max", type=int, default=None)
    ap.add_argument("--serve_batch_timeout_ms", type=float, default=None)
    ap.add_argument("--serve_queue_depth", type=int, default=None)
    ap.add_argument("--serve_deadline_ms", type=float, default=None)
    ap.add_argument("--serve_cache_size", type=int, default=0,
                    help="0 (default) keeps throughput numbers honest "
                         "on a repeating corpus")
    ap.add_argument("--telemetry_dir", default=None)
    ap.add_argument("--trace", action="store_true",
                    help="request-scoped tracing: queue -> batch -> "
                         "device -> decode span trees per request; "
                         "exports Chrome trace JSON after the run "
                         "(defaults --telemetry_dir to a temp dir "
                         "when unset)")
    ap.add_argument("--trace_out", default=None,
                    help="Chrome trace JSON path (default: "
                         "<run_dir>/trace.json)")
    ap.add_argument("--watchdog_stall_s", type=float, default=0.0,
                    help="stall watchdog deadline for the batcher "
                         "consumer (0 = off)")
    ap.add_argument("--watchdog_mode", default="warn",
                    choices=["warn", "raise"])
    ap.add_argument("--metrics_port", type=int, default=0,
                    help="serve /metrics //healthz //vars from the "
                         "PredictionServer while the load runs "
                         "(0 = off)")
    ap.add_argument("--alerts_mode", default="off",
                    choices=["off", "warn", "raise"],
                    help="serving health monitors (cache-hit "
                         "collapse, shed burn-rate) + alert rules "
                         "(defaults --telemetry_dir to a temp dir "
                         "when unset — alert events need a run dir)")
    ap.add_argument("--alerts_rules", default=None,
                    help="JSON alert-rule file (see README)")
    ap.add_argument("--out", default=None, help="also write JSON here")
    args = ap.parse_args(argv)
    if args.load and args.synthetic:
        ap.error("--load and --synthetic are mutually exclusive")
    if (args.trace or args.watchdog_stall_s > 0
            or args.alerts_mode != "off") and not args.telemetry_dir:
        # spans, stall dumps and alert events live in the run dir —
        # make one
        args.telemetry_dir = tempfile.mkdtemp(prefix="loadgen_trace_")

    cfg, model = _build_model(args)
    if args.telemetry_dir:
        cfg.TELEMETRY_DIR = args.telemetry_dir
    cfg.TRACE = bool(args.trace)
    cfg.WATCHDOG_STALL_S = args.watchdog_stall_s
    cfg.WATCHDOG_MODE = args.watchdog_mode
    cfg.METRICS_PORT = args.metrics_port
    cfg.ALERTS_MODE = args.alerts_mode
    cfg.ALERTS_RULES = args.alerts_rules

    if args.corpus:
        with open(args.corpus, encoding="utf-8") as f:
            flat = [ln for ln in f if ln.strip()]
        corpus = [flat[i:i + args.methods]
                  for i in range(0, len(flat), args.methods)]
        if args.requests and len(corpus) > args.requests:
            corpus = corpus[:args.requests]
    else:
        corpus = gen_corpus(args.requests, args.methods,
                            max_ctx=min(cfg.MAX_CONTEXTS, 12))

    from code2vec_tpu.obs import Telemetry
    from code2vec_tpu.serving.server import PredictionServer
    tele = Telemetry.create(cfg.TELEMETRY_DIR, config=cfg,
                            mesh=getattr(model, "mesh", None),
                            component="loadgen")
    if not tele.enabled:
        tele = Telemetry.memory("loadgen")
    tele.make_threadsafe()

    reports = []
    if args.mode in ("sequential", "compare"):
        model.warmup_predict(args.methods)  # compile the batch-1 bucket
        reports.append(run_sequential(model, corpus,
                                      duration=args.duration))
    if args.mode != "sequential":
        server = PredictionServer(cfg, model, telemetry=tele)
        server.start()
        compiled_after_warmup = model.predict_compile_count()
        mode = "closed" if args.mode == "compare" else args.mode
        rep = run_load(server, corpus, mode=mode,
                       concurrency=args.concurrency, qps=args.qps,
                       duration=args.duration,
                       arrivals=args.arrivals,
                       modulation=(None if args.modulation == "none"
                                   else args.modulation),
                       modulation_period_s=args.modulation_period_s,
                       hot_key_frac=args.hot_key_frac,
                       hot_keys=args.hot_keys, seed=args.seed)
        if compiled_after_warmup >= 0:
            rep["compiled_variants_after_warmup"] = compiled_after_warmup
            rep["new_compilations_under_load"] = (
                model.predict_compile_count() - compiled_after_warmup)
        else:
            # -1 sentinel: the jit cache is not introspectable here —
            # report unknown, never a false "0 compilations" pass
            rep["compiled_variants_after_warmup"] = None
            rep["new_compilations_under_load"] = None
        server.close()
        reports.append(rep)

    out = {"reports": reports}
    if args.mode == "compare" and len(reports) == 2:
        seq, bat = reports
        out["speedup"] = round(
            bat["throughput_rps"] / max(seq["throughput_rps"], 1e-9), 2)
    for rep in reports:
        tele.event("loadgen", **rep)
    tele.close()
    if args.trace and tele.run_dir:
        # export the run's spans as Chrome trace-event JSON (Perfetto /
        # chrome://tracing; tools/trace_report.py prints the
        # critical-path breakdown from the same run dir)
        from tools.trace_report import write_chrome_trace
        trace_out = args.trace_out or os.path.join(tele.run_dir,
                                                   "trace.json")
        n_events = write_chrome_trace([tele.run_dir], trace_out)
        out["trace_json"] = trace_out
        out["trace_events"] = n_events
        out["trace_run_dir"] = tele.run_dir
    text = json.dumps(out, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
