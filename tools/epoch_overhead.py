#!/usr/bin/env python3
"""Epoch-boundary stall: synchronous vs async checkpointing, measured.

ISSUE 5 acceptance driver. Every epoch boundary used to stall the chip
for the FULL wall time of a synchronous orbax save plus a full eval
plus an infeed cold restart. This tool trains the same tiny synthetic
model twice on the CPU mesh harness — `--async_checkpoint off` then
`on` — with per-run telemetry, and reports per boundary:

  - save_blocked_ms   loop-side blocked time (the submit + snapshot
                      dispatch under async; the whole save under sync)
  - save_total_ms     writer-side wall (snapshot fetch + serialize +
                      commit rename + pruning)
  - eval_ms           the epoch eval that overlaps the writer tail
  - boundary_ms       wall time from the last step event before the
                      boundary to the first step event after it — the
                      actual training gap
  - steps_during_save step events timestamped inside the async save
                      window (training demonstrably proceeding while
                      the writer drains; requires epochs >= 2)

plus the headline ratio: async blocked time as a fraction of the sync
save wall (< 0.25 is the acceptance bar).

Usage:
  python tools/epoch_overhead.py [--epochs 3] [--examples 768]
      [--batch 64] [--emb 64] [--max_contexts 16] [--no_eval]
      [--out boundaries.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_TOKENS = ["foo", "bar", "baz", "qux", "value", "name", "index", "count"]
_PATHS = [str(h) for h in (123456, -98765, 424242, 1337, -777, 31415)]
_TARGETS = ["get|value", "set|value", "get|name", "set|name", "add|item",
            "remove|item", "to|string", "is|empty"]


def _raw_lines(n: int, seed: int, max_ctx: int) -> List[str]:
    rng = random.Random(seed)
    lines = []
    for _ in range(n):
        t = rng.randrange(len(_TARGETS))
        ctxs = [f"{_TOKENS[(t + rng.randrange(2)) % len(_TOKENS)]},"
                f"{_PATHS[t % len(_PATHS)]},"
                f"{_TOKENS[(t * 3 + rng.randrange(2)) % len(_TOKENS)]}"
                for _ in range(rng.randint(1, max_ctx))]
        lines.append(_TARGETS[t] + " " + " ".join(ctxs))
    return lines


def build_dataset(tmpdir: str, n_train: int, max_contexts: int) -> str:
    """Synthetic extractor output -> preprocessed `.c2v` prefix (the
    tests/helpers recipe, standalone so the tool needs no test deps)."""
    from code2vec_tpu.data import preprocess as preprocess_mod
    paths = {}
    for split, n, seed in (("train", n_train, 1), ("val", 32, 2),
                           ("test", 64, 3)):
        p = os.path.join(tmpdir, f"raw.{split}.txt")
        with open(p, "w") as f:
            f.write("\n".join(_raw_lines(n, seed, max_contexts)) + "\n")
        paths[split] = p
    prefix = os.path.join(tmpdir, "tiny")
    preprocess_mod.main([
        "--train_data", paths["train"], "--val_data", paths["val"],
        "--test_data", paths["test"],
        "--max_contexts", str(max_contexts),
        "--word_vocab_size", "1000", "--path_vocab_size", "1000",
        "--target_vocab_size", "1000", "--output_name", prefix])
    return prefix


def analyze(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per-boundary metrics from one run's telemetry events."""
    from tools.telemetry_report import boundary_rows
    rows = boundary_rows(events)
    steps = sorted((e for e in events if e.get("kind") == "step"),
                   key=lambda e: e["ts"])
    saves = {int(e["step"]): e for e in events
             if e.get("kind") == "save" and "step" in e}
    commits = {int(e["step"]): e for e in events
               if e.get("kind") == "save_committed" and "step" in e}
    for r in rows:
        save_ev, commit_ev = saves.get(r["step"]), commits.get(r["step"])
        before = [e for e in steps if int(e["step"]) <= r["step"]]
        after = [e for e in steps if int(e["step"]) > r["step"]]
        r["boundary_ms"] = (
            round((after[0]["ts"] - before[-1]["ts"]) * 1e3, 1)
            if before and after else None)
        # async save window: the `save` event fires when the loop
        # unblocks (writer still draining), `save_committed` at the
        # rename — step events inside that window prove the loop ran
        # while the writer wrote
        n_during = 0
        if save_ev is not None and commit_ev is not None:
            n_during = sum(1 for e in after
                           if save_ev["ts"] <= e["ts"] <= commit_ev["ts"])
        r["steps_during_save"] = n_during
    return rows


def _read_events(run_dir: str) -> List[Dict[str, Any]]:
    out = []
    with open(os.path.join(run_dir, "events.jsonl"),
              encoding="utf-8") as f:
        for line in f:
            if line.strip():
                out.append(json.loads(line))
    return out


def run_mode(prefix: str, workdir: str, *, use_async: bool, epochs: int,
             batch: int, emb: int, max_contexts: int,
             with_eval: bool, trace: bool = False,
             trace_out: Optional[str] = None) -> List[Dict[str, Any]]:
    from code2vec_tpu.config import Config
    from code2vec_tpu.models.jax_model import Code2VecModel
    tag = "async" if use_async else "sync"
    cfg = Config(
        MAX_CONTEXTS=max_contexts, MAX_TOKEN_VOCAB_SIZE=1000,
        MAX_PATH_VOCAB_SIZE=1000, MAX_TARGET_VOCAB_SIZE=1000,
        DEFAULT_EMBEDDINGS_SIZE=emb, TRAIN_BATCH_SIZE=batch,
        TEST_BATCH_SIZE=batch, NUM_TRAIN_EPOCHS=epochs,
        SAVE_EVERY_EPOCHS=1, NUM_BATCHES_TO_LOG_PROGRESS=10_000,
        USE_BF16=False, ASYNC_CHECKPOINT=use_async, TRACE=trace,
        TELEMETRY_DIR=os.path.join(workdir, f"tele_{tag}"))
    cfg.train_data_path = prefix
    if with_eval:
        cfg.test_data_path = prefix + ".test.c2v"
    cfg.save_path = os.path.join(workdir, f"ckpt_{tag}")
    model = Code2VecModel(cfg)
    model.train()
    model.close_session()
    if trace and trace_out:
        # Chrome trace of the boundary: step_cycle spans on the loop
        # row, save_write on the ckpt-writer row, infeed/produce on the
        # producer row — the overlap the summary numbers claim, visible
        from tools.trace_report import write_chrome_trace
        n = write_chrome_trace([model.telemetry.run_dir], trace_out)
        print(json.dumps({"trace_json": trace_out, "mode": tag,
                          "trace_events": n}), flush=True)
    return analyze(_read_events(model.telemetry.run_dir))


def main(argv: Optional[List[str]] = None) -> Dict[str, Any]:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--examples", type=int, default=768)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--emb", type=int, default=64)
    ap.add_argument("--max_contexts", type=int, default=16)
    ap.add_argument("--warmup_boundaries", type=int, default=2,
                    help="boundaries excluded from the summary medians:"
                         " the first boundary's save overlaps the eval"
                         " jit compile (GIL starvation inflates the"
                         " writer wall) and the second inherits its"
                         " tail as blocked time — steady state starts"
                         " after them")
    ap.add_argument("--no_eval", action="store_true",
                    help="skip the per-epoch eval (isolates the save "
                         "overlap: next-epoch steps run during the "
                         "writer drain instead of eval)")
    ap.add_argument("--trace", action="store_true",
                    help="per-step span trees (--trace) for both "
                         "runs; writes epoch_overhead_trace_{sync,"
                         "async}.json Chrome traces to the cwd")
    ap.add_argument("--out", default=None, help="also append JSONL here")
    a = ap.parse_args(argv)

    result: Dict[str, Any] = {}
    with tempfile.TemporaryDirectory(prefix="epoch_overhead_") as wd:
        prefix = build_dataset(wd, a.examples, a.max_contexts)
        for tag, use_async in (("sync", False), ("async", True)):
            rows = run_mode(prefix, wd, use_async=use_async,
                            epochs=a.epochs, batch=a.batch, emb=a.emb,
                            max_contexts=a.max_contexts,
                            with_eval=not a.no_eval, trace=a.trace,
                            trace_out=(f"epoch_overhead_trace_{tag}"
                                       ".json") if a.trace else None)
            result[tag] = rows
            for r in rows:
                print(json.dumps({"mode": tag, **r}), flush=True)

    def med(vals):
        s = sorted(v for v in vals if v is not None and v == v)
        return s[len(s) // 2] if s else float("nan")

    # steady state only: the warmup boundaries measure jit-compile GIL
    # contention, not the checkpoint protocol
    w = max(0, min(a.warmup_boundaries, a.epochs - 1))
    sync_rows = result["sync"][w:]
    async_rows = result["async"][w:]
    sync_wall = med([r["blocked_ms"] for r in sync_rows])
    async_blocked = med([r["blocked_ms"] for r in async_rows])
    async_total = med([r["total_ms"] for r in async_rows])
    summary = {
        "warmup_boundaries_excluded": w,
        "sync_save_wall_ms_p50": round(sync_wall, 2),
        "async_blocked_ms_p50": round(async_blocked, 2),
        "async_total_ms_p50": round(async_total, 2),
        "blocked_vs_sync_wall": round(async_blocked / sync_wall, 4)
        if sync_wall == sync_wall and sync_wall > 0 else None,
        "sync_boundary_ms_p50": med(
            [r["boundary_ms"] for r in sync_rows]),
        "async_boundary_ms_p50": med(
            [r["boundary_ms"] for r in async_rows]),
        "async_steps_during_save": sum(
            r["steps_during_save"] for r in result["async"]),
    }
    result["summary"] = summary
    print(json.dumps({"summary": summary}), flush=True)
    if a.out:
        with open(a.out, "a", encoding="utf-8") as f:
            for tag in ("sync", "async"):
                for r in result[tag]:
                    f.write(json.dumps({"mode": tag, **r}) + "\n")
            f.write(json.dumps({"summary": summary}) + "\n")
    return result


if __name__ == "__main__":
    main()
