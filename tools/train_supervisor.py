#!/usr/bin/env python3
"""Restart supervisor CLI (ISSUE 10): wrap a training command with a
bounded restart budget, verified auto-resume, and coherent cohort
relaunch.

Usage (repo root):

  # single process, up to 3 restarts, resume from --save's checkpoints
  python tools/train_supervisor.py --max_restarts 3 -- \
      python code2vec.py --data d/ds --save ckpt --lr_schedule constant

  # a 2-process Gloo cohort on the CPU harness (4 virtual devices per
  # worker); the supervisor appends the --dist_* flags itself and
  # relaunches the WHOLE cohort on a fresh port when any member dies
  python tools/train_supervisor.py --procs 2 --cpu_devices 4 -- \
      python code2vec.py --data d/ds --save ckpt --lr_schedule constant

Everything after `--` is the child command. The supervisor:

  - appends `--auto_resume` when the child has `--save` but forgot the
    flag (a supervised run that restarts from scratch would defeat the
    point — announced, not silent);
  - verifies the checkpoint dir before EVERY launch, quarantining
    corrupt step dirs (training/checkpoint.verify_and_resolve) so the
    child resumes from the last VERIFIED committed step;
  - escalates through the alert engine (`--telemetry_dir` makes the
    `alert` / `supervisor_*` events durable JSONL);
  - hosts the fleet plane (ISSUE 17) behind `--fleet_port`: each
    member gets a fixed `--metrics_port` (base `--member_metrics_base`
    + process index), the supervisor-side collector scrapes them all,
    runs the clock handshake (members persist measured offsets into
    their run manifests for `trace_report --merge`), publishes
    cohort straggler/divergence/throughput gauges, and serves the
    aggregate on `http://localhost:<fleet_port>/fleet` (JSON;
    `?format=prom` for Prometheus text).

Exit codes: 0 = the supervised run completed; 3 = restart budget
exhausted; 2 = usage error.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _child_save_dir(child_cmd) -> str | None:
    for i, tok in enumerate(child_cmd):
        if tok == "--save" and i + 1 < len(child_cmd):
            return child_cmd[i + 1]
        if tok.startswith("--save="):
            return tok.split("=", 1)[1]
    return None


def main(argv=None) -> int:
    sys.path.insert(0, _REPO)
    ap = argparse.ArgumentParser(
        description="restart supervisor: <flags> -- <child command>")
    ap.add_argument("--max_restarts", type=int, default=3,
                    help="cohort relaunches before giving up (page "
                         "alert + exit 3)")
    ap.add_argument("--procs", type=int, default=1,
                    help="cohort size; >1 appends --dist_* flags per "
                         "member on a fresh port per attempt")
    ap.add_argument("--resize_policy", choices=("relaunch", "shrink"),
                    default="relaunch",
                    help="on peer death: 'relaunch' the whole cohort "
                         "at full size (PR-10 behavior) or 'shrink' — "
                         "re-form the mesh at N-1 processes (floor "
                         "--min_procs) and keep training (ISSUE 13)")
    ap.add_argument("--min_procs", type=int, default=1,
                    help="smallest cohort 'shrink' may re-form at")
    ap.add_argument("--cpu_devices", type=int, default=None,
                    help="pin this many virtual CPU devices per child "
                         "(the Gloo CPU harness) via the spawn env")
    ap.add_argument("--peer_grace_s", type=float, default=15.0,
                    help="after one member dies, how long the rest get "
                         "to exit on their own before SIGKILL")
    ap.add_argument("--attempt_timeout_s", type=float, default=None,
                    help="wall limit per attempt (unset = none)")
    ap.add_argument("--backoff_base_s", type=float, default=1.0,
                    help="restart backoff base (jittered exponential, "
                         "the shared resilience/retry math)")
    ap.add_argument("--telemetry_dir", default=None,
                    help="supervisor run telemetry (supervisor_* + "
                         "alert JSONL events)")
    ap.add_argument("--watchdog_stall_s", type=float, default=0.0,
                    help="with --telemetry_dir: stall watchdog over "
                         "the supervise loop; a missed deadline dumps "
                         "diagnostics INCLUDING the live cohort "
                         "topology (process set + target size)")
    ap.add_argument("--out_dir", default=None,
                    help="per-attempt child logs "
                         "(attempt<k>.proc<i>.log); default: inherit "
                         "stdio")
    ap.add_argument("--fleet_port", type=int, default=None,
                    help="host the cohort fleet collector (ISSUE 17) "
                         "and serve /fleet on this port (0 = any "
                         "free port); members get fixed "
                         "--metrics_port flags")
    ap.add_argument("--member_metrics_base", type=int, default=9200,
                    help="member i serves /metrics on base+i (the "
                         "fleet collector's scrape set)")
    ap.add_argument("--fleet_interval_s", type=float, default=2.0,
                    help="fleet collector sweep interval")
    ap.add_argument("child", nargs=argparse.REMAINDER,
                    help="-- <child command>")
    args = ap.parse_args(argv)

    child = list(args.child)
    if child and child[0] == "--":
        child = child[1:]
    if not child:
        ap.error("no child command given (put it after `--`)")

    from code2vec_tpu.obs import Telemetry
    from code2vec_tpu.resilience.retry import RetryPolicy
    from code2vec_tpu.training.supervisor import (RestartBudgetExceeded,
                                                  Supervisor,
                                                  build_cli_spawn)

    def log(msg: str) -> None:
        print(f"[train_supervisor] {msg}", flush=True)

    save_dir = _child_save_dir(child)
    if save_dir and "--auto_resume" not in child:
        log("child has --save but no --auto_resume; appending it "
            "(a supervised restart must resume, not retrain)")
        child.append("--auto_resume")

    telemetry = Telemetry.create(args.telemetry_dir,
                                 component="supervisor", log=log) \
        if args.telemetry_dir else None
    watchdog = None
    if args.watchdog_stall_s > 0 and telemetry is not None:
        from code2vec_tpu.obs import Watchdog
        watchdog = Watchdog.create(telemetry,
                                   stall_s=args.watchdog_stall_s,
                                   log=log).start()

    member_ports = None
    if args.fleet_port is not None:
        member_ports = [args.member_metrics_base + i
                        for i in range(args.procs)]

    sup = Supervisor(
        build_cli_spawn(child, num_procs=args.procs,
                        out_dir=args.out_dir,
                        cpu_devices=args.cpu_devices,
                        metrics_ports=member_ports, log=log),
        num_procs=args.procs, max_restarts=args.max_restarts,
        resize_policy=args.resize_policy, min_procs=args.min_procs,
        ckpt_dir=save_dir, telemetry=telemetry, watchdog=watchdog,
        log=log,
        peer_grace_s=args.peer_grace_s,
        attempt_timeout_s=args.attempt_timeout_s,
        backoff=RetryPolicy("supervisor-restart", max_attempts=1,
                            base_delay_s=args.backoff_base_s,
                            max_delay_s=60.0))
    fleet_server = None
    if member_ports is not None:
        from code2vec_tpu.obs import FleetCollector, MetricsServer
        members = [f"127.0.0.1:{p}" for p in member_ports]
        collector = FleetCollector.create(
            sup.telemetry, members=members,
            interval_s=args.fleet_interval_s, log=log)
        sup.attach_fleet(collector, members)
        # the supervisor's own /metrics (+ /fleet) endpoint: the
        # collector's fleet/* gauges live in sup.telemetry, so one
        # scrape of this port sees both the supervisor and the cohort
        port = args.fleet_port
        if port == 0:
            from code2vec_tpu.parallel.compat import free_port
            port = free_port()
        fleet_server = MetricsServer.create(
            sup.telemetry, port=port, fleet=collector,
            log=log).start()
    try:
        rc = sup.run()
    except RestartBudgetExceeded as e:
        log(str(e))
        rc = 3
    finally:
        if fleet_server is not None:
            fleet_server.stop()
        if watchdog is not None:
            watchdog.stop()
        if telemetry is not None:
            telemetry.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
