#!/usr/bin/env python3
"""Quality ablation: sampled softmax & low-precision vs the exact config.

BASELINE.md quality-evidence requirement (SURVEY.md §8.4 item 3): show on
a ≥50K-name corpus (tools/gen_java_corpus.py, extracted by the native
C++ extractor) that
  - sampled softmax matches full softmax F1 (the java-large config), and
  - bf16 tables / the adafactor table optimizer (the perf configs,
    BASELINE.md) match f32/adam F1
at matched steps, seeds, and data order.

Usage:
  python tools/gen_java_corpus.py --out /tmp/qs/raw ...
  TRAIN_DIR=... ./preprocess.sh   (see BASELINE.md)
  python tools/quality_study.py --data /tmp/qs/ds/qs --epochs 6 \
      [--variants full-f32-adam,sampled-f32-adam,...]
Prints one JSON line per variant and a summary table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

VARIANTS = {
    # name: (use_sampled, tables_dtype, embedding_optimizer, encoder)
    "full-f32-adam": (False, "float32", "adam", "bag"),
    "sampled-f32-adam": (True, "float32", "adam", "bag"),
    "sampled-bf16-adam": (True, "bfloat16", "adam", "bag"),
    "sampled-bf16-adafactor": (True, "bfloat16", "adafactor", "bag"),
    "sampled-int8-adafactor": (True, "int8", "adafactor", "bag"),
    "sampled-bf16-xf2": (True, "bfloat16", "adam", "transformer"),
}


def run_variant(name: str, data: str, epochs: int, batch: int,
                num_sampled: int, seed: int, lr: float = 1e-3,
                lr_schedule: str = "constant",
                max_contexts: int = 200,
                save_path: str = None,
                warmup_steps: int = 0,
                trust_ratio: bool = False,
                trust_ratio_scope: str = "all") -> dict:
    from code2vec_tpu.config import Config
    from code2vec_tpu.models.jax_model import Code2VecModel

    use_sampled, tdtype, eopt, encoder = VARIANTS[name]
    cfg = Config(
        MAX_CONTEXTS=max_contexts,
        MAX_TOKEN_VOCAB_SIZE=150_000,
        MAX_PATH_VOCAB_SIZE=150_000,
        MAX_TARGET_VOCAB_SIZE=60_000,
        TRAIN_BATCH_SIZE=batch,
        TEST_BATCH_SIZE=batch,
        NUM_TRAIN_EPOCHS=epochs,
        SAVE_EVERY_EPOCHS=1000,
        NUM_BATCHES_TO_LOG_PROGRESS=100,
        LEARNING_RATE=lr,
        LR_SCHEDULE=lr_schedule,
        LR_WARMUP_STEPS=warmup_steps,
        TRUST_RATIO=trust_ratio,
        TRUST_RATIO_SCOPE=trust_ratio_scope,
        SEED=seed,
        USE_SAMPLED_SOFTMAX=use_sampled,
        NUM_SAMPLED_CLASSES=num_sampled,
        TABLES_DTYPE=tdtype,
        EMBEDDING_OPTIMIZER=eopt,
        ENCODER_TYPE=encoder,
    )
    cfg.train_data_path = data
    cfg.test_data_path = data + ".val.c2v"
    cfg.verify()  # e.g. reject --warmup_steps with a non-warmup
    # schedule instead of recording a misleading combination
    model = Code2VecModel(cfg)
    t0 = time.time()
    model.train()
    train_s = time.time() - t0
    if save_path:
        # save OUTSIDE the timed window (a mid-train save cadence would
        # also trigger mid-train evaluate() calls and skew train_seconds
        # across variants)
        model.save(save_path)
    res = model.evaluate()
    out = {
        "variant": name,
        "use_sampled_softmax": use_sampled,
        "tables_dtype": tdtype,
        "embedding_optimizer": eopt,
        "encoder": encoder,
        "epochs": epochs,
        "batch": batch,
        "lr": lr,
        "lr_schedule": lr_schedule,
        "warmup_steps": warmup_steps,
        "trust_ratio": trust_ratio,
        "trust_ratio_scope": trust_ratio_scope,
        "max_contexts": max_contexts,
        "steps": model.step_num,
        "train_seconds": round(train_s, 1),
        "val_loss": round(float(res.loss), 4),
        "val_top1": round(res.topk_acc[0], 4),
        "val_top5": round(res.topk_acc[4], 4),
        "val_precision": round(res.subtoken_precision, 4),
        "val_recall": round(res.subtoken_recall, 4),
        "val_f1": round(res.subtoken_f1, 4),
        "target_vocab_size": model.vocabs.target_vocab.size,
    }
    print(json.dumps(out), flush=True)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", required=True)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch", type=int, default=1024,
                    help="batch size; with matched --epochs, different "
                         "batch sizes see the same token budget "
                         "(VERDICT r2 item 1a: large-batch convergence "
                         "neutrality)")
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--lr_schedule", default="constant",
                    choices=["constant", "cosine", "linear",
                             "warmup_cosine"])
    ap.add_argument("--warmup_steps", type=int, default=0,
                    help="warmup_cosine warmup length (0 = auto 5%%)")
    ap.add_argument("--trust_ratio", action="store_true",
                    help="LAMB-style per-array trust ratio")
    ap.add_argument("--trust_ratio_scope", default="all",
                    choices=["all", "dense"],
                    help="'dense' = trust-scale non-table params only "
                         "(the sane LAMB form; VERDICT r4 item 8)")
    ap.add_argument("--num_sampled", type=int, default=1024)
    ap.add_argument("--max_contexts", type=int, default=200,
                    help="match the dataset's binarized width (200 for "
                         "the production corpus; smaller for smokes)")
    ap.add_argument("--seed", type=int, default=239)
    ap.add_argument("--variants", default=",".join(VARIANTS))
    ap.add_argument("--save", default=None,
                    help="checkpoint dir prefix (enables the decay "
                         "study's per-epoch analysis)")
    ap.add_argument("--out", default=None,
                    help="append JSON lines here too")
    args = ap.parse_args()

    results = []
    for name in args.variants.split(","):
        r = run_variant(name.strip(), args.data, args.epochs, args.batch,
                        args.num_sampled, args.seed, lr=args.lr,
                        lr_schedule=args.lr_schedule,
                        max_contexts=args.max_contexts,
                        save_path=(args.save + "." + name.strip()
                                   if args.save else None),
                        warmup_steps=args.warmup_steps,
                        trust_ratio=args.trust_ratio,
                        trust_ratio_scope=args.trust_ratio_scope)
        results.append(r)
        if args.out:
            with open(args.out, "a") as f:
                f.write(json.dumps(r) + "\n")

    print("\nvariant                    B     lr      sched     F1      "
          "top1    loss")
    for r in results:
        print(f"{r['variant']:26s} {r['batch']:<5d} {r['lr']:<7g} "
              f"{r['lr_schedule']:9s} {r['val_f1']:.4f}  "
              f"{r['val_top1']:.4f}  {r['val_loss']:.3f}")


if __name__ == "__main__":
    main()
