#!/usr/bin/env python3
"""Row-block-size x vocab-size microbench for the fused Pallas
requantize row-pass (ops/pallas_requant.py) vs the multi-pass XLA
reference — the tuning driver for the kernel's _BLOCK_ROWS knob and
the per-phase attribution behind BASELINE.md's int8 requantize story.

Emits one JSON line per (vocab, block_rows) cell: fused ms, reference
ms, analytic bytes of one fused sweep (ops/pallas_requant.
requant_traffic_bytes) and the achieved GB/s, all slope-timed
(tools/_bench_common.slope_time — cancels the tunneled platform's
fixed dispatch cost).

Interpret-safe: off-TPU the kernel runs in Pallas interpreter mode, so
the default grid auto-shrinks to a smoke-scale sweep (off-TPU numbers
exercise the machinery, they do NOT attribute the chip). Tier-1 never
runs this — the pytest entry point is marked `slow`
(tests/test_requant_sweep.py; the tier-1 command deselects
`-m 'not slow'`).

Usage:
  python tools/requant_sweep.py \
      [--vocabs 65536,262144,1048576] [--blocks 128,256,512,1024] \
      [--emb 128] [--steps 20] [--out sweep.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--vocabs", default=None,
                    help="comma-separated table row counts")
    ap.add_argument("--blocks", default=None,
                    help="comma-separated kernel row-block sizes")
    ap.add_argument("--emb", type=int, default=128)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--out", default=None, help="also append JSONL here")
    a = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from code2vec_tpu.ops.pallas_requant import (requant_traffic_bytes,
                                                 requantize_fused)
    from code2vec_tpu.ops.quant import quantize_table, requantize_reference
    from tools._bench_common import slope_time

    # ONE jitted callable per implementation, hoisted out of the sweep
    # loops: different (vocab, block) cells retrace into the SAME shape-
    # keyed compile cache instead of rebuilding an empty-cache callable
    # per cell (the grandfathered graftlint retrace-hazard entries).
    ref_jit = jax.jit(requantize_reference)
    fused_jit = jax.jit(requantize_fused,
                        static_argnames=("block_rows",))

    on_tpu = jax.default_backend() == "tpu"
    # off-TPU the kernel interprets: shrink the default grid so the
    # sweep stays a smoke (the chip numbers come from a TPU run)
    vocabs = [int(x) for x in
              (a.vocabs or ("65536,262144,1048576" if on_tpu
                            else "2048")).split(",")]
    blocks = [int(x) for x in
              (a.blocks or ("128,256,512,1024" if on_tpu
                            else "128,256")).split(",")]
    warmup, base = (5, 10) if on_tpu else (1, 2)

    def timed_ms(fn, sync_key):
        """Slope-time `fn(rng) -> QuantTable` with pre-split keys and a
        scalar-transfer hard sync (the _bench_common contract)."""
        def chain(n, rng):
            rng, sub = jax.random.split(rng)
            keys = list(jax.random.split(sub, max(n, 1)))
            t0 = time.perf_counter()
            out = None
            for i in range(n):
                out = fn(keys[i])
            float(out["s"].ravel()[0])
            return time.perf_counter() - t0, rng
        return max(slope_time(chain, jax.random.PRNGKey(sync_key),
                              a.steps, warmup=warmup, base=base), 1e-9) \
            * 1e3

    rows = []
    for V in vocabs:
        r = np.random.default_rng(V)
        qt = quantize_table(jnp.asarray(
            r.normal(size=(V, a.emb)) * 0.3, jnp.float32))
        upd = jnp.asarray(r.normal(size=(V, a.emb)) * 1e-4, jnp.bfloat16)
        nbytes = requant_traffic_bytes(qt, upd)
        ref_ms = timed_ms(lambda rng: ref_jit(qt, upd, rng), 1)
        for br in blocks:
            fused_ms = timed_ms(
                lambda rng, br=br: fused_jit(qt, upd, rng,
                                             block_rows=br), 2)
            row = {"vocab": V, "emb": a.emb, "block_rows": br,
                   "mode": "tpu" if on_tpu else "interpret",
                   "fused_ms": round(fused_ms, 3),
                   "reference_ms": round(ref_ms, 3),
                   "sweep_bytes": int(nbytes),
                   "fused_gbps": round(
                       nbytes / (fused_ms / 1e3) / 1e9, 2)}
            rows.append(row)
            print(json.dumps(row), flush=True)

    if a.out:
        with open(a.out, "a", encoding="utf-8") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
