#!/usr/bin/env python3
"""Method-coverage measurement for the native C++ extractor.

SURVEY.md §8.4 item 1: the hand-written Java grammar "must still hit
high method coverage; mitigate with golden corpus + coverage stats".
This tool produces the stats: it generates a corpus with a KNOWN method
count (tools/gen_java_corpus.py is deterministic), runs the extractor
CLI over it, and reports extraction coverage plus context-count
distribution. Round-2 reference point: 249,996 / 250,000 methods
(99.998%) on the default corpus.

Usage:
  python tools/extractor_coverage.py [--methods 20000] [--dir <.java dir>
      --expected N]   # --dir skips generation and measures your corpus
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from code2vec_tpu.extractor.native import _BIN_PATH as EXTRACTOR


def measure(java_dir: str, expected: int, num_threads: int = 4) -> dict:
    out = subprocess.run(
        [EXTRACTOR, "--dir", java_dir, "--max_path_length", "8",
         "--max_path_width", "2", "--num_threads", str(num_threads)],
        capture_output=True, text=True)
    if out.returncode != 0:
        sys.exit(f"extractor failed (rc={out.returncode}):\n"
                 f"{out.stderr}")
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    ctx_counts = [len(ln.split(" ")) - 1 for ln in lines]
    ctx_counts.sort()
    n = len(lines)
    pct = lambda p: ctx_counts[min(n - 1, int(p * n))] if n else 0
    return {
        "methods_expected": expected,
        "methods_extracted": n,
        "coverage": round(n / expected, 5) if expected else None,
        "contexts_per_method": {
            "p10": pct(0.10), "p50": pct(0.50), "p90": pct(0.90),
            "max": ctx_counts[-1] if n else 0},
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--methods", type=int, default=20_000)
    ap.add_argument("--dir", default=None,
                    help="measure an existing .java corpus instead of "
                         "generating one")
    ap.add_argument("--expected", type=int, default=0,
                    help="known method count for --dir")
    ap.add_argument("--num_threads", type=int, default=4)
    args = ap.parse_args()

    if not os.path.exists(EXTRACTOR):
        sys.exit(f"extractor not built ({EXTRACTOR}); run "
                 "./build_extractor.sh")

    if args.dir:
        if args.expected <= 0:
            sys.exit("--dir requires --expected N (the known method "
                     "count) — coverage is the whole point of the tool")
        stats = measure(args.dir, args.expected, args.num_threads)
    else:
        with tempfile.TemporaryDirectory() as tmp:
            gen = subprocess.run(
                [sys.executable,
                 os.path.join(os.path.dirname(__file__),
                              "gen_java_corpus.py"),
                 "--out", tmp, "--methods", str(args.methods),
                 "--names", str(min(5000, args.methods // 4))],
                capture_output=True, text=True)
            if gen.returncode != 0:
                sys.exit(f"corpus generation failed:\n{gen.stderr}")
            # the generator prints its exact written count — parse it
            # rather than re-deriving the split math
            m = re.search(r"total: (\d+) methods", gen.stdout)
            if not m:
                sys.exit(f"could not parse generator output:\n"
                         f"{gen.stdout}")
            stats = measure(tmp, int(m.group(1)), args.num_threads)
    import json
    print(json.dumps(stats, indent=1))


if __name__ == "__main__":
    main()
