#!/usr/bin/env python3
"""Render a traced telemetry run (`--trace`, code2vec_tpu/obs/trace.py)
as Chrome trace-event JSON and critical-path breakdowns.

Usage:
  python tools/trace_report.py <telemetry_dir | run_dir> [run_dir...]
      [--chrome trace.json] [--limit N]
  python tools/trace_report.py --merge <run_dir>... --chrome out.json
      # one Chrome/Perfetto trace for a multi-process cohort
      # (per-run process_name/pid metadata; aligned on the fleet
      # handshake's MEASURED clock offsets when every manifest has a
      # `clock` block, else the created_unix fallback + clock_note
      # caveat) — telemetry_report.py --merge applied to traces

Reads the run's `events.jsonl` (the `kind="span"` records the tracer
emits) and produces:

  - `--chrome <out.json>`: Chrome trace-event format, viewable in
    Perfetto (ui.perfetto.dev) or chrome://tracing. One row per real
    thread (named) plus virtual tracks (e.g. the serving queue); spans
    are complete ("X") events carrying their trace/span ids in args;
    cross-trace links (a batcher flush serving several requests, a
    step consuming a producer-thread infeed batch) become flow events
    ("s"/"f" pairs) so a request can be followed THROUGH the flush
    that served it.
  - per-request critical-path table: one row per `serve/request` trace
    with queue_wait / parse / encode / device / decode ms (encode and
    device come from the batch flush that served the request — by
    trace id for the flush's primary request, by link for coalesced
    ones) plus aggregate p50/p95/p99 per phase.
  - per-step table: infeed_wait / step / save_blocked (+ the writer's
    save_write wall) from the `train/step_cycle` traces.

Pure stdlib; reads only manifest + events files, so it works on a
laptop over a run dir scp'd from a pod (same contract as
tools/telemetry_report.py, which this reuses for run discovery).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.telemetry_report import find_runs, load_run  # noqa: E402

PCTS = (50, 95, 99)

# phase order of the serving critical path (the table's columns)
REQUEST_PHASES = ("queue_wait", "parse", "encode", "device", "decode")
_REQ_SPAN = {"serve/queue_wait": "queue_wait", "serve/parse": "parse",
             "serve/encode": "encode", "serve/device": "device",
             "serve/decode": "decode", "serve/extract": "extract"}
STEP_PHASES = ("infeed_wait", "step")


def load_spans(run_dirs: Sequence[str]
               ) -> List[Tuple[Dict[str, Any], List[Dict[str, Any]]]]:
    """[(manifest, span_events)] per run, span events only."""
    out = []
    for d in run_dirs:
        manifest, events = load_run(d)
        out.append((manifest,
                    [e for e in events if e.get("kind") == "span"]))
    return out


# ---------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------

def chrome_trace_events(loaded: Sequence[Tuple[Dict[str, Any],
                                               List[Dict[str, Any]]]],
                        merge: bool = False) -> List[Dict[str, Any]]:
    """Spans -> Chrome trace events. ts/dur are microseconds relative
    to the earliest span across all runs (the tracer's monotonic `t0`
    is only meaningful within a process; cross-run alignment uses each
    run's own base — good enough for same-process run sets, which is
    what a traced run directory holds).

    `merge` (ISSUE 15: `--merge <run_dir>...`, the telemetry_report
    --merge shape applied to traces) renders a multi-PROCESS cohort as
    ONE trace: each run keeps its manifest process_index as the Chrome
    pid (collisions fall back to a fresh id), gets a `process_name`
    metadata row (run_id + component), and its timeline is offset onto
    a shared wall clock.

    Alignment comes in two qualities. When EVERY run's manifest
    carries the `clock` block the fleet handshake commits (ISSUE 17:
    paired monotonic+wall readings plus the collector-MEASURED
    wall-clock offset, obs/fleet.py), span timelines convert from the
    tracer's monotonic timebase to the collector's wall clock exactly:
    `t0 - clock.mono` re-bases the span onto the paired reading, `+
    clock.wall - clock.wall_offset_s` lands it on the collector's
    clock — cross-process gaps are then real to handshake precision
    (sub-ms on a LAN) and the old caveat is retired. Without measured
    clocks the pre-17 fallback applies: offset by the manifests'
    `created_unix`, only as good as host wall sync + manifest-to-
    first-span latency, and each process carries a `clock_note`
    instant event saying exactly that, so nobody reads a 2 ms
    cross-host gap as truth."""
    events: List[Dict[str, Any]] = []
    flow_id = 0
    used_pids: Dict[int, int] = {}
    # measured path: every run with spans must carry a handshake clock
    # block — a half-measured cohort would interleave exact and sloppy
    # timelines as if they were comparable
    clocks = [m.get("clock") for m, s in loaded if s]
    measured = bool(clocks) and all(
        isinstance(c, dict)
        and all(k in c for k in ("mono", "wall", "wall_offset_s"))
        for c in clocks)
    if merge and measured:
        corrected = []
        for manifest, spans in loaded:
            if not spans:
                continue
            c = manifest["clock"]
            base = min(float(s["t0"]) for s in spans)
            corrected.append(base - float(c["mono"]) + float(c["wall"])
                             - float(c["wall_offset_s"]))
        wall0 = min(corrected, default=None)
    else:
        wall = [m.get("created_unix") for m, s in loaded if s]
        wall0 = min((w for w in wall if w is not None), default=None)
    for run_idx, (manifest, spans) in enumerate(loaded):
        if not spans:
            continue
        pid = int(manifest.get("process_index", run_idx))
        base = min(float(s["t0"]) for s in spans)
        offset_us = 0.0
        if merge and measured and wall0 is not None:
            c = manifest["clock"]
            offset_us = (base - float(c["mono"]) + float(c["wall"])
                         - float(c["wall_offset_s"]) - wall0) * 1e6
        elif merge and wall0 is not None \
                and manifest.get("created_unix") is not None:
            offset_us = (float(manifest["created_unix"]) - wall0) * 1e6
        if merge:
            while pid in used_pids:  # two runs claiming one index
                pid += 1000
            used_pids[pid] = run_idx
            name_args: Dict[str, Any] = {
                "name": f"p{manifest.get('process_index', '?')}"
                        f" {manifest.get('run_id', '?')}"
                        f" ({manifest.get('component', '?')})"}
            if measured:
                name_args["clock_offset_s"] = float(
                    manifest["clock"]["wall_offset_s"])
            events.append({"name": "process_name", "ph": "M",
                           "pid": pid, "args": name_args})
        if merge and not measured:
            events.append({
                "name": "clock_note", "cat": "meta", "ph": "I",
                "s": "p", "pid": pid, "tid": 0,
                "ts": round(offset_us, 3),
                "args": {"note": "timeline offset from manifest "
                                 "created_unix (monotonic clocks are "
                                 "per-process): cross-process skew = "
                                 "host wall-clock sync + manifest-to-"
                                 "first-span latency; run under the "
                                 "fleet plane (ISSUE 17) to commit "
                                 "MEASURED offsets instead"}})
        by_id: Dict[str, Dict[str, Any]] = {s["span"]: s for s in spans}
        seen_threads: Dict[int, str] = {}
        for s in spans:
            tid = int(s.get("tid", 0))
            tname = str(s.get("tname", ""))
            if tid not in seen_threads:
                seen_threads[tid] = tname
                events.append({"name": "thread_name", "ph": "M",
                               "pid": pid, "tid": tid,
                               "args": {"name": tname}})
            ts = (float(s["t0"]) - base) * 1e6 + offset_us
            dur = max(float(s.get("dur_ms", 0.0)) * 1e3, 1.0)
            args = {"trace": s.get("trace"), "span": s.get("span")}
            if s.get("parent"):
                args["parent"] = s["parent"]
            args.update(s.get("attrs") or {})
            events.append({"name": s["name"], "cat": "span", "ph": "X",
                           "pid": pid, "tid": tid,
                           "ts": round(ts, 3), "dur": round(dur, 3),
                           "args": args})
            # cross-trace links -> flow events (s on the SOURCE span's
            # row, f at this span's start): the request -> flush edges
            for link in s.get("links") or ():
                src = by_id.get(link[1])
                if src is None:
                    continue
                flow_id += 1
                src_ts = (float(src["t0"]) - base) * 1e6 + offset_us
                src_dur = max(float(src.get("dur_ms", 0.0)) * 1e3, 1.0)
                # bind inside the source slice: at the flow target's
                # start when that falls within it, else at the edge
                bind = min(max(ts, src_ts), src_ts + src_dur)
                events.append({"name": "handoff", "cat": "flow",
                               "ph": "s", "id": flow_id, "pid": pid,
                               "tid": int(src.get("tid", 0)),
                               "ts": round(bind, 3)})
                events.append({"name": "handoff", "cat": "flow",
                               "ph": "f", "bp": "e", "id": flow_id,
                               "pid": pid, "tid": tid,
                               "ts": round(ts, 3)})
    return events


def write_chrome_trace(run_dirs: Sequence[str], out_path: str,
                       merge: bool = False) -> int:
    """Write the Chrome trace JSON for the given run dirs; returns the
    number of trace events written. `merge` = one cohort trace (see
    chrome_trace_events)."""
    events = chrome_trace_events(load_spans(run_dirs), merge=merge)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return len(events)


# ---------------------------------------------------------------------
# critical-path breakdowns
# ---------------------------------------------------------------------

def request_breakdowns(spans: Sequence[Dict[str, Any]]
                       ) -> List[Dict[str, Any]]:
    """One row per `serve/request` trace: total plus per-phase ms.

    The flush's encode/device spans live in the flush's OWN trace (the
    first coalesced request's); other requests reach them through the
    flush's links. Both paths attribute the same flush to the request,
    so coalesced requests each see the shared device cost — a critical
    -path view (what this request waited on), not a cost accounting."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        by_trace.setdefault(s["trace"], []).append(s)
    # flush span -> its child phase durations
    flush_children: Dict[str, Dict[str, float]] = {}
    flushes: List[Dict[str, Any]] = []
    for s in spans:
        if s["name"] == "serve/batch_flush":
            flushes.append(s)
            flush_children[s["span"]] = {}
    for s in spans:
        parent = s.get("parent")
        phase = _REQ_SPAN.get(s["name"])
        if parent in flush_children and phase:
            d = flush_children[parent]
            d[phase] = d.get(phase, 0.0) + float(s["dur_ms"])
    # request root span id -> flushes that served it (via trace OR link)
    serving_flush: Dict[str, List[Dict[str, Any]]] = {}
    for f in flushes:
        serving_flush.setdefault(f["trace"], []).append(f)
    linked_flush: Dict[str, List[Dict[str, Any]]] = {}
    for f in flushes:
        for link in f.get("links") or ():
            linked_flush.setdefault(link[0], []).append(f)
    rows = []
    for trace_id, group in sorted(by_trace.items()):
        root = next((s for s in group
                     if s["name"] == "serve/request"), None)
        if root is None:
            continue
        row: Dict[str, Any] = {"trace": trace_id,
                               "total_ms": float(root["dur_ms"]),
                               "n_methods": (root.get("attrs") or {}
                                             ).get("n_methods")}
        for s in group:
            phase = _REQ_SPAN.get(s["name"])
            # flush children (encode/device) share the PRIMARY
            # request's trace — they're attributed via flush_children
            # below, so counting them here would double the primary's
            # figures vs its coalesced siblings'
            if phase and s.get("parent") not in flush_children:
                row[phase] = row.get(phase, 0.0) + float(s["dur_ms"])
        for f in (serving_flush.get(trace_id, ())
                  or linked_flush.get(trace_id, ())):
            for phase, ms in flush_children.get(f["span"], {}).items():
                row[phase] = row.get(phase, 0.0) + ms
        rows.append(row)
    return rows


def step_breakdowns(spans: Sequence[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
    """One row per `train/step_cycle` trace: infeed_wait / step ms (+
    step number); `train/save_blocked` and the writer's
    `train/save_write` report as their own rows keyed by step."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        by_trace.setdefault(s["trace"], []).append(s)
    rows = []
    for trace_id, group in sorted(by_trace.items()):
        root = next((s for s in group
                     if s["name"] == "train/step_cycle"), None)
        if root is None:
            continue
        row = {"trace": trace_id,
               "step": (root.get("attrs") or {}).get("step"),
               "total_ms": float(root["dur_ms"])}
        for s in group:
            if s["name"] == "train/infeed_wait":
                row["infeed_wait"] = float(s["dur_ms"])
            elif s["name"] == "train/step":
                row["step_ms"] = float(s["dur_ms"])
        rows.append(row)
    return rows


def save_breakdowns(spans: Sequence[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
    rows = []
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        by_trace.setdefault(s["trace"], []).append(s)
    for trace_id, group in sorted(by_trace.items()):
        root = next((s for s in group
                     if s["name"] == "train/save_blocked"), None)
        if root is None:
            continue
        write = next((s for s in group
                      if s["name"] == "train/save_write"), None)
        rows.append({
            "step": (root.get("attrs") or {}).get("step"),
            "save_blocked_ms": float(root["dur_ms"]),
            "save_write_ms": (float(write["dur_ms"])
                              if write is not None else None),
        })
    return rows


# ---------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------

def _pct(values: List[float], p: float) -> float:
    if not values:
        return float("nan")
    s = sorted(values)
    k = int(round(p / 100.0 * (len(s) - 1)))
    return s[max(0, min(len(s) - 1, k))]


def _fmt(v, nd: int = 2) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        if v != v:
            return "—"
        return f"{v:,.{nd}f}"
    return str(v)


def render(loaded, limit: int = 10) -> str:
    lines: List[str] = []
    for manifest, spans in loaded:
        rid = manifest.get("run_id", "?")
        lines.append(f"## run {rid} "
                     f"({manifest.get('component', '?')}, "
                     f"{len(spans)} spans)")
        req_rows = request_breakdowns(spans)
        if req_rows:
            lines.append("")
            lines.append("| Request (trace) | methods | "
                         + " | ".join(REQUEST_PHASES)
                         + " | total ms |")
            lines.append("|---" * (len(REQUEST_PHASES) + 3) + "|")
            for r in req_rows[:limit]:
                lines.append(
                    f"| {r['trace']} | {_fmt(r.get('n_methods'))} | "
                    + " | ".join(_fmt(r.get(p)) for p in REQUEST_PHASES)
                    + f" | {_fmt(r['total_ms'])} |")
            if len(req_rows) > limit:
                lines.append(f"| … {len(req_rows) - limit} more "
                             f"requests elided (--limit) |"
                             + " |" * (len(REQUEST_PHASES) + 2))
            lines.append("")
            lines.append("| Phase (all requests) | p50 ms | p95 ms "
                         "| p99 ms |")
            lines.append("|---|---|---|---|")
            for phase in REQUEST_PHASES + ("total_ms",):
                vals = [r[phase] for r in req_rows if phase in r]
                if not vals:
                    continue
                lines.append(f"| {phase} | "
                             + " | ".join(_fmt(_pct(vals, p))
                                          for p in PCTS) + " |")
        step_rows = step_breakdowns(spans)
        if step_rows:
            lines.append("")
            lines.append("| Step phase | n | p50 ms | p95 ms "
                         "| p99 ms |")
            lines.append("|---|---|---|---|---|")
            for key in ("infeed_wait", "step_ms", "total_ms"):
                vals = [r[key] for r in step_rows if key in r]
                if vals:
                    lines.append(f"| {key} | {len(vals)} | "
                                 + " | ".join(_fmt(_pct(vals, p))
                                              for p in PCTS) + " |")
        save_rows = save_breakdowns(spans)
        if save_rows:
            lines.append("")
            lines.append("| Save (step) | blocked ms | writer ms |")
            lines.append("|---|---|---|")
            for r in save_rows:
                lines.append(f"| {_fmt(r['step'])} "
                             f"| {_fmt(r['save_blocked_ms'])} "
                             f"| {_fmt(r['save_write_ms'])} |")
        if not (req_rows or step_rows or save_rows):
            lines.append("")
            lines.append("(no request or step traces — was the run "
                         "started with --trace?)")
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="render traced telemetry runs (Chrome trace JSON "
                    "+ critical-path breakdowns)")
    ap.add_argument("paths", nargs="+",
                    help="telemetry root dir(s) or run dir(s)")
    ap.add_argument("--chrome", default=None,
                    help="also write Chrome trace-event JSON here "
                         "(Perfetto / chrome://tracing)")
    ap.add_argument("--merge", action="store_true",
                    help="treat the given run dirs as ONE multi-"
                         "process cohort and write a single Chrome "
                         "trace: per-run process_name/pid metadata, "
                         "timelines aligned on the fleet handshake's "
                         "measured clock offsets when every manifest "
                         "carries one (ISSUE 17), else on "
                         "created_unix with a clock_note caveat "
                         "event. Requires --chrome.")
    ap.add_argument("--limit", type=int, default=10,
                    help="per-request rows to print before eliding")
    args = ap.parse_args(argv)
    if args.merge and not args.chrome:
        print("error: --merge produces a merged Chrome trace; pass "
              "--chrome <out.json>", file=sys.stderr)
        return 2
    run_dirs: List[str] = []
    for p in args.paths:
        found = find_runs(p)
        if not found:
            print(f"error: no telemetry runs under {p}",
                  file=sys.stderr)
            return 2
        run_dirs.extend(found)
    loaded = load_spans(run_dirs)
    if args.chrome:
        n = write_chrome_trace(run_dirs, args.chrome,
                               merge=args.merge)
        print(f"chrome trace: {n} events -> {args.chrome}"
              + (f" (merged cohort of {len(run_dirs)} runs)"
                 if args.merge else ""))
    sys.stdout.write(render(loaded, limit=args.limit))
    return 0


if __name__ == "__main__":
    sys.exit(main())
