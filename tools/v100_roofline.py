#!/usr/bin/env python3
"""Derive the V100 baseline denominator for BASELINE.md / bench.py.

The north star (BASELINE.json) is "≥8x single-V100 throughput on
java-large". No V100 exists in this environment, so the denominator is
derived, not measured on-device — but every input is either an analytic
property of the reference's step (SURVEY.md §3: fp32, full softmax,
dense Adam) or a published V100 spec, and the assumptions all FAVOR the
reference (free input pipeline, good cuBLAS efficiency, full overlap
credit where plausible):

  t_step >= matmul_flops / (peak_fp32 * gemm_eff) + bandwidth_terms/BW

tools/tf_baseline.py measures the same graph math in TF 2.21 on this
host, anchoring the analytic FLOP model against a real TF execution
(achieved GFLOPs within the expected fraction of host GEMM peak).

Run: python tools/v100_roofline.py  -> one JSON line with the band.
"""

from __future__ import annotations

import json

# V100 SXM2 published specs
PEAK_FP32 = 15.7e12        # FLOP/s
HBM_BW = 900e9             # B/s

# reference step shape (SURVEY.md §3 config row)
B = 1024
C = 200
E = 128
D = 3 * E                  # 384
V_TOKEN = 1_301_136
V_PATH = 911_417
V_TARGET = 261_245

# cuBLAS efficiency band for K=384-ish GEMMs of these shapes
GEMM_EFF_OPTIMISTIC = 0.70
GEMM_EFF_REALISTIC = 0.50

F32 = 4


def derive(gemm_eff: float) -> dict:
    # ---- matmul FLOPs (fwd; bwd ~ 2x) ----
    transform = 2.0 * B * C * D * D
    attention = 2.0 * B * C * D
    logits = 2.0 * B * D * V_TARGET
    matmul = 3.0 * (transform + attention + logits)
    t_matmul = matmul / (PEAK_FP32 * gemm_eff)

    # ---- bandwidth terms not hidden behind the matmuls (separate
    # kernels in the reference's non-XLA TF1 graph) ----
    logits_tensor = B * V_TARGET * F32
    t_softmax_ce = 3.0 * logits_tensor / HBM_BW      # fwd read+write, bwd
    gathers = 2.0 * 3 * B * C * E * F32              # read + write
    t_gathers = gathers / HBM_BW
    ctx_tensor = B * C * D * F32
    t_elementwise = 8.0 * ctx_tensor / HBM_BW        # concat/dropout/tanh
    params = (V_TOKEN * E + V_PATH * E + V_TARGET * D) * F32
    t_adam = 7.0 * params / HBM_BW                   # p,g,m,v r/w passes
    dense_grad = (V_TOKEN * E + V_PATH * E) * F32
    t_scatter = (dense_grad + gathers / 2) / HBM_BW  # zero-init + adds

    t_total = (t_matmul + t_softmax_ce + t_gathers + t_elementwise
               + t_adam + t_scatter)
    ex_s = B / t_total
    return {
        "gemm_eff": gemm_eff,
        "ms_per_step": round(t_total * 1e3, 1),
        "ms_matmul": round(t_matmul * 1e3, 1),
        "ms_adam": round(t_adam * 1e3, 1),
        "examples_per_sec": round(ex_s, 0),
        "path_contexts_per_sec": round(ex_s * C, -3),
    }


def main() -> None:
    opt = derive(GEMM_EFF_OPTIMISTIC)
    real = derive(GEMM_EFF_REALISTIC)
    mid = (opt["path_contexts_per_sec"]
           + real["path_contexts_per_sec"]) / 2
    print(json.dumps({
        "model": "reference step on V100 (fp32, full softmax, dense "
                 "Adam, input pipeline assumed free)",
        "optimistic": opt,
        "realistic": real,
        "adopted_denominator_path_contexts_per_sec": round(mid, -4),
        "community_anecdote_lower_bound": 700_000,
    }))


if __name__ == "__main__":
    main()
