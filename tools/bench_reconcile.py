#!/usr/bin/env python3
"""Reconcile bench.py's full-step time against the phase floors
(VERDICT r3 weak #1/#2: bench measured 30.8 ms/step while the round-3
phase study reported 26.0 ms for "the same" config — a 4.8 ms gap,
larger than the whole adafactor optimizer phase, blamed on hand-wavy
"variance + batch rotation").

This tool slope-times ONE factor at a time, all with the shipped
java-large adafactor config (bf16 tables, sampled S=4096, Pallas pool
on TPU), so the residual decomposes into named, measured pieces:

  A  full step, 1 device-resident batch, keys pre-split   (phase-study
     conditions, but on bench's exact dims/optimizer build)
  B  full step, 4-batch rotation, keys pre-split          (bench.py
     conditions)
  C  full step, 1 batch, jax.random.split INSIDE the loop (the round-3
     profile_step.py loop shape — dispatch-cost probe)
  D  fwd+bwd only, 1 batch vs 4-batch rotation            (is the
     rotation effect in the backward scatter or the optimizer?)

Also prints the round-3 discrepancy suspects it can falsify:
  - profile_step.py's ModelDims defaulted tables_dtype to float32
    while BASELINE.md labeled the phase floors "bf16 tables" — A is
    measured at BOTH dtypes so the 26.0 ms row can be attributed.

Usage: python tools/bench_reconcile.py [--steps 40]
One JSON line per measurement + a summary table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _bench_common import (BATCH as B, CTX, NUM_SAMPLED, PATH_VOCAB,  # noqa: E402
                           TARGET_VOCAB, TOKEN_VOCAB, slope_time)


def _dims(tables_dtype: str):
    from code2vec_tpu.models.encoder import ModelDims
    return ModelDims(token_vocab_size=TOKEN_VOCAB,
                     path_vocab_size=PATH_VOCAB,
                     target_vocab_size=TARGET_VOCAB,
                     embeddings_size=128, max_contexts=CTX,
                     tables_dtype=tables_dtype)


def _batches(n: int):
    import jax.numpy as jnp
    r = np.random.default_rng(0)
    out = []
    for _ in range(n):
        out.append(tuple(jnp.asarray(a) for a in (
            r.integers(0, TARGET_VOCAB, (B,), dtype=np.int32),
            r.integers(0, TOKEN_VOCAB, (B, CTX), dtype=np.int32),
            r.integers(0, PATH_VOCAB, (B, CTX), dtype=np.int32),
            r.integers(0, TOKEN_VOCAB, (B, CTX), dtype=np.int32),
            np.ones((B, CTX), np.float32),
            np.ones((B,), np.float32))))
    return out


def time_full_step(dims, n_batches: int, split_in_loop: bool,
                   steps: int) -> float:
    import jax
    import jax.numpy as jnp

    from code2vec_tpu.models.encoder import init_params
    from code2vec_tpu.training.optimizers import make_optimizer
    from code2vec_tpu.training.steps import make_train_step

    params = init_params(jax.random.PRNGKey(0), dims)
    opt = make_optimizer(1e-3)  # shipped default: adafactor tables
    step = make_train_step(dims, opt, use_sampled_softmax=True,
                           num_sampled=NUM_SAMPLED,
                           compute_dtype=jnp.bfloat16,
                           use_pallas=jax.default_backend() == "tpu")
    batches = _batches(n_batches)

    def chain(n, state):
        params, opt_state, rng = state
        if not split_in_loop:
            rng, sub = jax.random.split(rng)
            keys = list(jax.random.split(sub, max(n, 1)))
        t0 = time.perf_counter()
        for i in range(n):
            if split_in_loop:
                rng, k = jax.random.split(rng)
            else:
                k = keys[i]
            params, opt_state, loss = step(
                params, opt_state, batches[i % n_batches], k)
        float(loss)
        return time.perf_counter() - t0, (params, opt_state, rng)

    state = (params, opt.init(params), jax.random.PRNGKey(1))
    return slope_time(chain, state, steps)


def time_fwd_bwd(dims, n_batches: int, steps: int) -> float:
    import jax
    import jax.numpy as jnp

    from code2vec_tpu.models.encoder import init_params
    from code2vec_tpu.training.steps import make_train_loss_fn

    params = init_params(jax.random.PRNGKey(0), dims)
    loss_fn = make_train_loss_fn(
        dims, use_sampled_softmax=True, num_sampled=NUM_SAMPLED,
        compute_dtype=jnp.bfloat16,
        use_pallas=jax.default_backend() == "tpu")
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    batches = _batches(n_batches)

    def chain(n, rng):
        rng, sub = jax.random.split(rng)
        keys = list(jax.random.split(sub, max(n, 1)))
        t0 = time.perf_counter()
        for i in range(n):
            loss, _g = grad_fn(params, batches[i % n_batches], keys[i])
        float(loss)
        return time.perf_counter() - t0, rng

    return slope_time(chain, jax.random.PRNGKey(3), steps)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    args = ap.parse_args()

    rows = []

    def rec(name, dt):
        row = {"case": name, "ms_per_step": round(dt * 1e3, 2),
               "pc_per_sec": round(B * CTX / dt, 1)}
        rows.append(row)
        print(json.dumps(row), flush=True)

    bf16 = _dims("bfloat16")
    f32 = _dims("float32")

    rec("A_full_1batch_presplit_bf16",
        time_full_step(bf16, 1, False, args.steps))
    rec("A32_full_1batch_presplit_f32",
        time_full_step(f32, 1, False, args.steps))
    rec("B_full_4batch_presplit_bf16  [bench.py conditions]",
        time_full_step(bf16, 4, False, args.steps))
    rec("C_full_1batch_splitinloop_bf16  [profile_step.py loop shape]",
        time_full_step(bf16, 1, True, args.steps))
    rec("D1_fwdbwd_1batch_bf16", time_fwd_bwd(bf16, 1, args.steps))
    rec("D4_fwdbwd_4batch_bf16", time_fwd_bwd(bf16, 4, args.steps))

    a = rows[0]["ms_per_step"]
    b = rows[2]["ms_per_step"]
    c = rows[3]["ms_per_step"]
    d1, d4 = rows[4]["ms_per_step"], rows[5]["ms_per_step"]
    print(f"\nrotation cost (B-A):          {b - a:+.2f} ms/step")
    print(f"split-in-loop cost (C-A):     {c - a:+.2f} ms/step")
    print(f"rotation cost in fwd+bwd:     {d4 - d1:+.2f} ms/step")
    print(f"optimizer phase (A-D1):       {a - d1:+.2f} ms/step")


if __name__ == "__main__":
    main()
