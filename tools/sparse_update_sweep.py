#!/usr/bin/env python3
"""Block-size x id-count x vocab microbench for the fused Pallas
live-row sparse update (ops/pallas_sparse_update.py) vs the XLA
gather/scatter reference — the tuning driver for the facade's
_BLOCK_ROWS knob and the per-phase attribution behind BASELINE.md's
round-13 sparse-update story (the requant_sweep playbook one level
up).

Emits one JSON line per (vocab, n_ids, block_rows) cell: fused ms,
reference ms, the analytic [U, E]-aware bytes of one apply
(training/sparse_update.sparse_update_traffic_bytes at the cell's
MEASURED unique-row count) and the achieved GB/s, all slope-timed
(tools/_bench_common.slope_time — cancels the tunneled platform's
fixed dispatch cost). The timed callable is the exact facade
composition the sparse train step runs: dedup + segment-sum + live-row
apply, state threaded through a donated jit so the in-place aliasing
matches production.

Interpret-safe: off-TPU the kernel runs in Pallas interpreter mode, so
the default grid auto-shrinks to a smoke-scale sweep (off-TPU numbers
exercise the machinery, they do NOT attribute the chip). Tier-1 never
runs this — the pytest entry point is marked `slow`
(tests/test_sparse_update_sweep.py; the tier-1 command deselects
`-m 'not slow'`).

Usage:
  python tools/sparse_update_sweep.py \
      [--vocabs 65536,262144,1048576] [--blocks 128,256,512,1024] \
      [--ids 409600] [--emb 128] [--dtype bfloat16|float32|int8] \
      [--steps 20] [--out sweep.jsonl]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--vocabs", default=None,
                    help="comma-separated table row counts")
    ap.add_argument("--blocks", default=None,
                    help="comma-separated kernel row-block sizes")
    ap.add_argument("--ids", type=int, default=None,
                    help="gathered ids per apply (default: 2*B*C on "
                         "TPU — the token-table workload — else a "
                         "smoke count)")
    ap.add_argument("--emb", type=int, default=128)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32", "int8"],
                    help="table storage dtype (int8 sweeps the "
                         "requantize-aware row update)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--out", default=None, help="also append JSONL here")
    a = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from code2vec_tpu.ops.quant import quantize_table
    from code2vec_tpu.training import sparse_update as su
    from code2vec_tpu.training.sparse_adam import init_row_adam
    from tools._bench_common import BATCH, CTX, slope_time

    on_tpu = jax.default_backend() == "tpu"
    vocabs = [int(x) for x in
              (a.vocabs or ("65536,262144,1048576" if on_tpu
                            else "2048")).split(",")]
    blocks = [int(x) for x in
              (a.blocks or ("128,256,512,1024" if on_tpu
                            else "128,256")).split(",")]
    n_ids = a.ids if a.ids is not None else \
        (2 * BATCH * CTX if on_tpu else 4096)
    warmup, base = (5, 10) if on_tpu else (1, 2)
    quantized = a.dtype == "int8"
    dtype = jnp.bfloat16 if a.dtype == "bfloat16" else jnp.float32

    # ONE donated jitted callable per table layout, hoisted out of the
    # sweep loops: different (vocab, block) cells retrace into the SAME
    # shape/static-keyed compile cache instead of rebuilding an
    # empty-cache callable per cell (the requant_sweep lesson). The
    # donation makes the fused path's input->output aliasing real, as
    # in the production train step.
    @functools.partial(jax.jit, donate_argnums=(0, 1),
                       static_argnames=("fused", "block_rows"))
    def apply_float(table, state, count, ids, grads, fused, block_rows):
        t, s = su.sparse_row_adam(table, state, ids, grads, count=count,
                                  lr=1e-3, fused=fused,
                                  block_rows=block_rows)
        return t, s, count + 1

    @functools.partial(jax.jit, donate_argnums=(0, 1),
                       static_argnames=("fused", "block_rows"))
    def apply_int8(qt, state, count, ids, grads, rng, fused, block_rows):
        t, s = su.sparse_requant_adam(qt, state, ids, grads, rng,
                                      count=count, lr=1e-3, fused=fused,
                                      block_rows=block_rows)
        return t, s, count + 1

    def timed_ms(make_state, run_one):
        """Slope-time `run_one(state, key) -> state` threading the
        donated (table, state, count) chain, hard-synced via a scalar
        host transfer (the _bench_common contract)."""
        def chain(n, st):
            state, rng = st
            rng, sub = jax.random.split(rng)
            keys = list(jax.random.split(sub, max(n, 1)))
            t0 = time.perf_counter()
            for i in range(n):
                state = run_one(state, keys[i])
            tbl = state[0]["s"] if quantized else state[0]
            float(tbl.ravel()[0])
            return time.perf_counter() - t0, (state, rng)
        return max(slope_time(chain, (make_state(),
                                      jax.random.PRNGKey(3)),
                              a.steps, warmup=warmup, base=base),
                   1e-9) * 1e3

    rows = []
    for V in vocabs:
        r = np.random.default_rng(V)
        base_tbl = jnp.asarray(r.normal(size=(V, a.emb)) * 0.3,
                               jnp.float32)
        table = quantize_table(base_tbl) if quantized \
            else base_tbl.astype(dtype)
        ids = jnp.asarray(r.integers(0, V, n_ids), jnp.int32)
        grads = jnp.asarray(r.normal(size=(n_ids, a.emb)) * 1e-3,
                            jnp.bfloat16 if not quantized
                            and dtype == jnp.bfloat16 else jnp.float32)
        unique_rows = int(np.unique(np.asarray(ids)).size)
        grad_itemsize = grads.dtype.itemsize

        def make_state(table=table):
            return (jax.tree_util.tree_map(jnp.copy, table),
                    init_row_adam(table), jnp.asarray(1, jnp.int32))

        for br in blocks:
            nbytes = su.sparse_update_traffic_bytes(
                table, n_ids, unique_rows,
                grad_itemsize=grad_itemsize, block_rows=br)

            def run_one(fused):
                if quantized:
                    return lambda st, k: apply_int8(
                        st[0], st[1], st[2], ids, grads, k,
                        fused=fused, block_rows=br)
                return lambda st, k: apply_float(
                    st[0], st[1], st[2], ids, grads,
                    fused=fused, block_rows=br)

            ref_ms = timed_ms(make_state, run_one(False))
            fused_ms = timed_ms(make_state, run_one(True))
            row = {"vocab": V, "emb": a.emb, "n_ids": n_ids,
                   "dtype": a.dtype, "block_rows": br,
                   "mode": "tpu" if on_tpu else "interpret",
                   "unique_rows": unique_rows,
                   "fused_ms": round(fused_ms, 3),
                   "reference_ms": round(ref_ms, 3),
                   "update_bytes": int(nbytes),
                   "fused_gbps": round(
                       nbytes / (fused_ms / 1e3) / 1e9, 2)}
            rows.append(row)
            print(json.dumps(row), flush=True)

    if a.out:
        with open(a.out, "a", encoding="utf-8") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
