#!/usr/bin/env python3
"""Phase-level profile of the TRANSFORMER (configs[4]) training step.

VERDICT r3 item 4: the transformer config benches at 1.04x the V100
baseline with no engineering behind the number — no phase breakdown of
its ~100 ms step, no roofline statement. This tool slope-times each
phase of the xf2 java-large step (B=1024, C=200, D=384, H=4, L=2,
bf16 compute) and compares against a MEASURED MXU peak (big bf16
matmul on this chip, not a quoted spec), so the output answers: is the
step MXU-bound, HBM-bound, or idle?

Phases:
  matmul peak    dense [8192x8192]@[8192x8192] bf16 -> measured TFLOP/s
  emb gathers    3 embedding takes + concat + in_proj
  xf fwd         full encoder forward (layers + pool)
  attn core      the L x H attention blocks alone (qkv/logits/softmax/
                 out on real shapes) — the Pallas-candidate region
  mlp core       the L MLP blocks alone
  loss fwd       encoder + sampled softmax head
  fwd+bwd        value_and_grad of the loss
  full step      shipped adafactor train step

Analytic FLOPs for each phase give achieved TFLOP/s and MXU
utilization; the attention row also prints the [B,H,C,C] logits HBM
bytes the XLA path materializes (the traffic a fused kernel removes).

Usage: python tools/xf_profile.py [--steps 30] [--layers 2]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _bench_common import (BATCH as B, CTX, NUM_SAMPLED, PATH_VOCAB,  # noqa: E402
                           TARGET_VOCAB, TOKEN_VOCAB, slope_time,
                           time_fn)

E = 128


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=3)  # shipped default
    # (hd=128 lane-aligned; --heads 4 reproduces the round-4
    # before/after comparison)
    args = ap.parse_args()
    L, H = args.layers, args.heads

    import jax
    import jax.numpy as jnp

    from code2vec_tpu.models.encoder import ModelDims, init_params
    from code2vec_tpu.models.transformer_encoder import (_mha, _rms_norm,
                                                         encode_transformer)
    from code2vec_tpu.training.optimizers import make_optimizer
    from code2vec_tpu.training.steps import (make_train_loss_fn,
                                             make_train_step)

    dims = ModelDims(token_vocab_size=TOKEN_VOCAB,
                     path_vocab_size=PATH_VOCAB,
                     target_vocab_size=TARGET_VOCAB,
                     embeddings_size=E, max_contexts=CTX,
                     tables_dtype="bfloat16",
                     encoder_type="transformer", xf_layers=L,
                     xf_heads=H)
    D = dims.context_vector_size  # 3E = 384
    MLP = dims.xf_mlp_ratio * D
    params = init_params(jax.random.PRNGKey(0), dims)

    r = np.random.default_rng(0)
    labels = jnp.asarray(r.integers(0, TARGET_VOCAB, (B,), np.int32))
    src = jnp.asarray(r.integers(0, TOKEN_VOCAB, (B, CTX), np.int32))
    pth = jnp.asarray(r.integers(0, PATH_VOCAB, (B, CTX), np.int32))
    dst = jnp.asarray(r.integers(0, TOKEN_VOCAB, (B, CTX), np.int32))
    mask = jnp.ones((B, CTX), jnp.float32)
    weights = jnp.ones((B,), jnp.float32)
    batch = (labels, src, pth, dst, mask, weights)
    x_bcd = jnp.asarray(r.normal(size=(B, CTX, D)), jnp.bfloat16)
    log_mask = jnp.zeros((B, CTX), jnp.float32)

    rows = []

    def rec(name, dt, flops=None, extra=None):
        row = {"phase": name, "ms": round(dt * 1e3, 2)}
        if flops:
            row["tflops_per_sec"] = round(flops / dt / 1e12, 1)
        if extra:
            row.update(extra)
        rows.append(row)
        print(json.dumps(row), flush=True)
        return row

    # ---- measured MXU peak ----
    M = 8192
    a = jnp.asarray(r.normal(size=(M, M)), jnp.bfloat16)
    bmat = jnp.asarray(r.normal(size=(M, M)), jnp.bfloat16)
    mm = jax.jit(lambda a, b: a @ b)
    dt = time_fn(mm, (a, bmat), args.steps)
    peak = 2 * M**3 / dt
    peak_row = rec("matmul_peak_bf16", dt, flops=2 * M**3)

    # ---- embedding gathers + in_proj ----
    @jax.jit
    def emb_fn(params, src, pth, dst):
        e = jnp.concatenate([
            jnp.take(params["token_emb"], src, axis=0),
            jnp.take(params["path_emb"], pth, axis=0),
            jnp.take(params["token_emb"], dst, axis=0),
        ], axis=-1).astype(jnp.bfloat16)
        return e @ params["xf"]["in_proj"].astype(jnp.bfloat16)

    dt = time_fn(emb_fn, (params, src, pth, dst), args.steps)
    rec("emb_gathers_in_proj", dt, flops=2 * B * CTX * D * D)

    # ---- attention core (L layers of pre-LN MHA on real shapes) ----
    xf = params["xf"]

    @jax.jit
    def attn_fn(x):
        for layer in xf["layers"]:
            h = _rms_norm(x, layer["ln1_scale"])
            x = x + _mha(h, layer["qkv"], layer["out"], log_mask, H)
        return x

    attn_flops = L * (2 * B * CTX * D * 3 * D      # qkv
                      + 2 * 2 * B * H * CTX * CTX * (D // H)  # qk, av
                      + 2 * B * CTX * D * D)       # out
    logits_bytes = L * B * H * CTX * CTX * 4       # f32 materialization
    dt = time_fn(attn_fn, (x_bcd,), args.steps)
    rec("attn_core_fwd", dt, flops=attn_flops,
        extra={"xla_logits_hbm_bytes": logits_bytes})

    # ---- MLP core ----
    @jax.jit
    def mlp_fn(x):
        for layer in xf["layers"]:
            h = _rms_norm(x, layer["ln2_scale"])
            h = jax.nn.gelu(h @ layer["mlp_up"].astype(jnp.bfloat16))
            x = x + h @ layer["mlp_down"].astype(jnp.bfloat16)
        return x

    mlp_flops = L * 2 * 2 * B * CTX * D * MLP
    dt = time_fn(mlp_fn, (x_bcd,), args.steps)
    rec("mlp_core_fwd", dt, flops=mlp_flops)

    # ---- encoder fwd / loss fwd / fwd+bwd / full step ----
    @jax.jit
    def enc_fn(params, src, pth, dst, mask):
        code, _ = encode_transformer(params, src, pth, dst, mask,
                                     dims=dims,
                                     compute_dtype=jnp.bfloat16)
        return code

    dt = time_fn(enc_fn, (params, src, pth, dst, mask), args.steps)
    enc_flops = (2 * B * CTX * D * D + attn_flops + mlp_flops
                 + 2 * B * CTX * D)
    rec("encoder_fwd", dt, flops=enc_flops)

    head_flops = 2 * B * (NUM_SAMPLED + 1) * D
    rng = jax.random.PRNGKey(1)
    on_tpu = jax.default_backend() == "tpu"

    def measure_variant(tag, use_pallas):
        """Build + time one attention path's loss/grad/step. A factory
        so each variant's jits are evaluated once, outside the tag loop
        (graftlint retrace-hazard burndown: the two variants need
        genuinely different callables — use_pallas changes the program
        — so per-variant construction is the honest structure)."""
        loss_fn = make_train_loss_fn(dims, use_sampled_softmax=True,
                                     num_sampled=NUM_SAMPLED,
                                     compute_dtype=jnp.bfloat16,
                                     use_pallas=use_pallas)
        fwd = jax.jit(loss_fn)
        dt = time_fn(fwd, (params, batch, rng), args.steps,
                     sync=lambda o: float(o))
        rec(f"loss_fwd_{tag}", dt, flops=enc_flops + head_flops)

        grad_fn = jax.jit(jax.value_and_grad(loss_fn))
        dt = time_fn(grad_fn, (params, batch, rng), args.steps,
                     sync=lambda o: float(o[0]))
        fb = rec(f"fwd_bwd_{tag}", dt,
                 flops=3 * (enc_flops + head_flops))

        opt = make_optimizer(1e-3)
        step = make_train_step(dims, opt, use_sampled_softmax=True,
                               num_sampled=NUM_SAMPLED,
                               compute_dtype=jnp.bfloat16,
                               use_pallas=use_pallas)

        def chain(n, state):
            p, s, rng = state
            rng, sub = jax.random.split(rng)
            keys = list(jax.random.split(sub, max(n, 1)))
            t0 = time.perf_counter()
            for i in range(n):
                p, s, loss = step(p, s, batch, keys[i])
            float(loss)
            return time.perf_counter() - t0, (p, s, rng)

        # the chained step DONATES its params/opt_state — feed it
        # copies or the next tag's measurements read deleted arrays
        p0 = jax.tree_util.tree_map(jnp.copy, params)
        dt = slope_time(
            chain, (p0, opt.init(p0), jax.random.PRNGKey(2)),
            args.steps)
        full = rec(f"full_step_adafactor_{tag}", dt,
                   flops=3 * (enc_flops + head_flops),
                   extra={"pc_per_sec": round(B * CTX / dt, 1)})
        return fb, full

    # both attention paths: XLA einsum+softmax vs the fused Pallas
    # kernel pair (ops/xf_attention.py) — the before/after of the
    # [B,H,C,C] HBM materialization. Off-TPU only XLA runs (interpret
    # mode would measure the interpreter).
    fb, full = measure_variant("xla", False)
    if on_tpu:
        fb, full = measure_variant("pallas", True)

    # ---- roofline statement ----
    util = (full["tflops_per_sec"]
            / peak_row["tflops_per_sec"])
    print(f"\nmeasured bf16 matmul peak: "
          f"{peak_row['tflops_per_sec']} TFLOP/s")
    print(f"full step achieved:        {full['tflops_per_sec']} "
          f"TFLOP/s = {util:.0%} of measured peak")
    print(f"fwd+bwd achieved:          {fb['tflops_per_sec']} TFLOP/s "
          f"= {fb['tflops_per_sec'] / peak_row['tflops_per_sec']:.0%}")


if __name__ == "__main__":
    main()
