#!/usr/bin/env python3
"""Gate the latest BENCH round against the trajectory (ISSUE 7
satellite).

The driver captures one `BENCH_r<N>.json` per round; regressions so
far have been caught by a human reading BASELINE.md. This tool makes
the check mechanical:

  python tools/bench_regression.py            # repo root, defaults
  python tools/bench_regression.py --dir . --band 0.05

For each gated metric (higher-is-better throughput figures, plus a
LOWER_IS_BETTER set — the elastic-recovery costs — where the band
flips into a ceiling), the LATEST round is compared against the
MEDIAN of the previous `--window` rounds that report the metric. The tolerance band is the
larger of `--band` (the noise floor — slope timing on the tunneled
platform jitters a few percent run-to-run) and the observed relative
spread of those prior rounds (median absolute deviation × 2 / median),
so a historically noisy metric doesn't cry wolf and a historically
flat one stays tight. Exit codes: 0 = no regression (or not enough
history), 1 = regression, 2 = usage error. `--strict` makes
insufficient history an error instead of a pass.

Accepts both file shapes: the driver wrapper (`{"parsed": {...}}`)
and bench.py's bare result object.

`--kind multichip` gates the MULTICHIP_r*.json trajectory the same
way (tools/multichip_bench.py's scaling-efficiency rounds; the gated
set is MULTICHIP_METRICS). Seed rounds that are driver failure
records ({rc, ok, tail} — no metrics) are skipped like any other
result-free file.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

# higher-is-better figures gated by default; ms_per_step & friends are
# redundant inverses of these. Schema growth rule: rounds predating a
# metric (e.g. the round-13 `sparse_*` family) simply lack the key —
# they are excluded from that metric's history and the LATEST round
# gates on the metrics it actually reports (older rounds effectively
# gate on `value` and whatever else they carry); a missing or
# non-numeric key is never fatal to the gate.
# Per-phase attribution of the sparse step (ISSUE 15): bench.py emits
# phase_<name>_ms every round; gated LOWER-is-better so a single phase
# regressing 2x fails the gate even while the headline pc/s holds
# (slack created by one phase's win can hide another's regression in
# any whole-step figure). These literals are the canonical set;
# default-set runs (no --metrics) additionally auto-gate ANY other
# phase_*_ms key the rounds carry (a mesh capture's allreduce pair,
# the int8 backward_apply remainder), so no phase escapes the gate.
PHASE_MS_METRICS = ("phase_embed_gather_ms", "phase_concat_dense_ms",
                    "phase_forward_pool_ms", "phase_backward_ms",
                    "phase_table_apply_ms")

DEFAULT_METRICS = ("value", "int8_pc_per_sec", "transformer_pc_per_sec",
                   "fwd_bwd_floor_pc_per_sec", "sparse_pc_per_sec"
                   ) + PHASE_MS_METRICS

# The MULTICHIP trajectory (tools/multichip_bench.py, round 14):
# scaling efficiency is the headline — a pod that got faster per chip
# but lost more to the process boundary is a regression this gate must
# see; multi_pc_per_sec catches absolute multi-leg slowdowns the ratio
# could mask (both legs regressing together). The kill-mid-run leg
# (ISSUE 13) adds the recovery-cost pair — gated LOWER-is-better: a
# re-form that loses more steps or takes longer to reach its first
# post-resize step is the regression. host_skew_ratio (ISSUE 17) is
# the cohort-evenness gate: worst member step p50 over the cohort
# median — a straggler host taxes every step through the lock-step
# all-reduce, and the ratio catches it even when the summed
# throughput still squeaks past its floor.
MULTICHIP_METRICS = ("scaling_efficiency", "multi_pc_per_sec",
                     "recovery_steps_lost", "recovery_seconds",
                     "host_skew_ratio")

# The SERVING trajectory (tools/serving_bench.py, ISSUE 18): the
# client-observed tail and the sustained completion rate through the
# whole external plane (HTTP front-end -> replica pool -> batcher ->
# device). p99 is the SLO figure — gated LOWER-is-better; req/s
# catches an absolute throughput slide the tail could mask (queue
# shrinks because everything sheds).
SERVING_METRICS = ("serving_p99_ms", "serving_req_per_sec")

# Metrics where SMALLER is healthier: the band becomes a ceiling
# (baseline * (1 + band)) instead of a floor. Everything else in the
# gate — median baseline, MAD-widened band, history windowing — is
# direction-agnostic. Any phase_*_ms key rides the same direction via
# _lower_is_better (per-phase device times are costs, not throughput).
LOWER_IS_BETTER = frozenset({"recovery_steps_lost",
                             "recovery_seconds",
                             "host_skew_ratio",
                             "serving_p99_ms"})


def _lower_is_better(metric: str) -> bool:
    return metric in LOWER_IS_BETTER or (
        metric.startswith("phase_") and metric.endswith("_ms"))

KINDS = {
    "bench": ("BENCH_r*.json", DEFAULT_METRICS),
    "multichip": ("MULTICHIP_r*.json", MULTICHIP_METRICS),
    "serving": ("SERVING_r*.json", SERVING_METRICS),
}


def _round_re(pattern: str) -> "re.Pattern[str]":
    """`BENCH_r*.json` -> a regex capturing the round number."""
    return re.compile(
        re.escape(pattern).replace(r"\*", r"(\d+)") + "$")


def load_rounds(dir_path: str, pattern: str = "BENCH_r*.json"
                ) -> List[Tuple[int, Dict[str, Any]]]:
    """[(round_n, result_dict)] sorted by round. Files that carry no
    result (a failed round's wrapper — e.g. the seed MULTICHIP rounds,
    whose shape is the driver's {rc, ok, tail} failure record) are
    skipped, not fatal."""
    round_re = _round_re(pattern)
    rounds = []
    for path in glob.glob(os.path.join(dir_path, pattern)):
        m = round_re.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path, encoding="utf-8") as f:
                obj = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"warning: skipping unreadable {path}: {e}",
                  file=sys.stderr)
            continue
        result = obj.get("parsed") if isinstance(obj, dict) else None
        if result is None and isinstance(obj, dict) \
                and ("value" in obj
                     or obj.get("schema") in ("multichip", "serving")):
            result = obj  # bench/multichip/serving bare round object
        if not isinstance(result, dict):
            print(f"warning: {path} carries no parsed bench result; "
                  "skipped", file=sys.stderr)
            continue
        rounds.append((int(m.group(1)), result))
    rounds.sort()
    return rounds


def _num(res: Dict[str, Any], metric: str) -> Optional[float]:
    """The metric's finite numeric value, or None when the round
    predates the metric (mixed-schema history) or carries a
    non-numeric placeholder — either way the round is excluded from
    this metric's series instead of crashing the gate."""
    v = res.get(metric)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    v = float(v)
    return v if v == v and v not in (float("inf"), float("-inf")) \
        else None


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def check_metric(metric: str, history: List[Tuple[int, float]],
                 latest_round: int, latest: float,
                 band_floor: float, min_history: int
                 ) -> Dict[str, Any]:
    """One metric's verdict row. `history` excludes the latest round.
    LOWER_IS_BETTER metrics regress when the latest rises ABOVE the
    banded ceiling; everything else when it falls below the floor."""
    row: Dict[str, Any] = {"metric": metric, "round": latest_round,
                           "latest": latest}
    if len(history) < min_history:
        row.update(status="skip",
                   note=f"history {len(history)} < {min_history}")
        return row
    values = [v for _r, v in history]
    baseline = _median(values)
    lower_better = _lower_is_better(metric)
    # a non-positive baseline means broken data for a throughput
    # metric — but for a lower-is-better COST metric, 0 is the best
    # possible baseline (perfect recovery) and any positive latest is
    # exactly the regression the gate exists for
    if (baseline <= 0 and not lower_better) \
            or (lower_better and baseline < 0):
        row.update(status="skip", note="non-positive baseline")
        return row
    mad = _median([abs(v - baseline) for v in values])
    band = band_floor if baseline == 0 \
        else max(band_floor, 2.0 * mad / baseline)
    if lower_better:
        bound = baseline * (1.0 + band)
        regressed = latest > bound
    else:
        bound = baseline * (1.0 - band)
        regressed = latest < bound
    row.update(baseline=baseline, band=band, floor=bound,
               lower_is_better=lower_better,
               ratio=latest / baseline if baseline > 0 else None,
               status="REGRESSION" if regressed else "ok",
               history_rounds=[r for r, _v in history])
    return row


def run(dir_path: str, metrics: List[str], band: float, window: int,
        min_history: int, strict: bool,
        pattern: str = "BENCH_r*.json",
        auto_phases: bool = False) -> Tuple[int, List[Dict]]:
    rounds = load_rounds(dir_path, pattern)
    if not rounds:
        print(f"error: no {pattern} with results under "
              f"{dir_path}", file=sys.stderr)
        return 2, []
    latest_round, latest = rounds[-1]
    prior = rounds[:-1]
    if auto_phases:
        # default-set runs gate EVERY phase_*_ms key the rounds carry,
        # not just the PHASE_MS_METRICS literals: a future capture
        # growing a phase (phase_allreduce_ms under a mesh, the int8
        # backward_apply remainder) must not escape the gate the docs
        # promise. An explicit --metrics list is respected as given.
        metrics = list(metrics) + sorted({
            k for _r, res in rounds for k in res
            if _lower_is_better(k) and k.startswith("phase_")
            and k not in metrics})
    rows = []
    for metric in metrics:
        latest_val = _num(latest, metric)
        if latest_val is None:
            rows.append({"metric": metric, "round": latest_round,
                         "status": "skip",
                         "note": ("non-numeric in latest round"
                                  if metric in latest
                                  else "absent from latest round")})
            continue
        history = [(r, v) for r, res in prior
                   for v in [_num(res, metric)]
                   if v is not None][-window:]
        rows.append(check_metric(metric, history, latest_round,
                                 latest_val, band, min_history))
    regressed = [r for r in rows if r["status"] == "REGRESSION"]
    skipped = [r for r in rows if r["status"] == "skip"]
    if strict and len(skipped) == len(rows):
        print("error: --strict and no metric had enough history",
              file=sys.stderr)
        return 2, rows
    return (1 if regressed else 0), rows


def render(rows: List[Dict[str, Any]]) -> str:
    lines = ["| Metric | latest | baseline (median) | floor/ceiling "
             "(band) | ratio | verdict |",
             "|---|---|---|---|---|---|"]

    def f(v, nd=1):
        return "—" if v is None else f"{v:,.{nd}f}"

    for r in rows:
        if r["status"] == "skip":
            lines.append(f"| {r['metric']} | {f(r.get('latest'))} "
                         f"| — | — | — | skip: {r['note']} |")
            continue
        ratio = ("—" if r.get("ratio") is None
                 else f"{r['ratio']:.3f}")
        lines.append(
            f"| {r['metric']} | {f(r['latest'])} "
            f"| {f(r['baseline'])} "
            f"| {f(r['floor'])} ({r['band'] * 100:.1f}%) "
            f"| {ratio} | {r['status']} |")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compare the latest BENCH_r*.json against the "
                    "round trajectory; exit 1 on regression")
    ap.add_argument("--dir", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--kind", choices=sorted(KINDS), default="bench",
                    help="which round trajectory to gate: 'bench' = "
                         "BENCH_r*.json single-chip rounds, "
                         "'multichip' = MULTICHIP_r*.json "
                         "scaling-efficiency rounds, 'serving' = "
                         "SERVING_r*.json external-plane rounds "
                         "(p99 ceiling + req/s floor)")
    ap.add_argument("--metrics", nargs="+", default=None,
                    help="result keys to gate (higher is better); "
                         "default: the --kind's gated set")
    ap.add_argument("--band", type=float, default=0.05,
                    help="noise-band floor as a fraction (the "
                         "tolerance is max of this and the history's "
                         "observed spread)")
    ap.add_argument("--window", type=int, default=5,
                    help="how many prior rounds form the baseline")
    ap.add_argument("--min_history", type=int, default=2,
                    help="prior rounds required before a metric is "
                         "gated at all")
    ap.add_argument("--strict", action="store_true",
                    help="fail (exit 2) when NO metric has enough "
                         "history, instead of passing quietly")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable row dump instead of the "
                         "table")
    args = ap.parse_args(argv)
    pattern, kind_metrics = KINDS[args.kind]
    metrics = args.metrics if args.metrics is not None \
        else list(kind_metrics)
    rc, rows = run(args.dir, metrics, args.band, args.window,
                   args.min_history, args.strict, pattern=pattern,
                   auto_phases=args.metrics is None)
    if rows:
        print(json.dumps(rows, indent=1) if args.json
              else render(rows))
    if rc == 1:
        print("REGRESSION: latest bench round fell below the "
              "trajectory floor", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
