#!/usr/bin/env python3
"""Serving-plane round capture (ISSUE 18): drive the HTTP front-end +
replica pool with the open-loop traffic model and write a
`SERVING_r<N>.json` round that `tools/bench_regression.py --kind
serving` gates (`serving_p99_ms` lower-is-better, `serving_req_per_sec`
higher-is-better).

The measured path is the WHOLE external plane: urllib POST /predict ->
front-end JSON translation -> least-outstanding dispatch -> micro-batch
-> device -> decode -> serialize, with client-side latency timing (the
number a real caller sees, not the in-process request_ms). The driver
reuses `tools/loadgen.run_load` by presenting the HTTP endpoint as a
`predict_lines` surface that raises `ServerOverloaded` on 429 — sheds
stay explicitly counted, exactly like the in-process runs.

    python tools/serving_bench.py --out SERVING_r01.json

builds a tiny synthetic model (the loadgen recipe), serves it from
`--replicas` replicas on an ephemeral port, offers `--qps` Poisson
arrivals with hot-key skew for `--requests` requests, and records the
round plus the zero-new-compilations check.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools import loadgen  # noqa: E402


class HttpPredictClient:
    """`run_load`'s server surface over the wire: predict_lines posts
    to the front-end, 429 re-raises as ServerOverloaded so the load
    report's ok/shed/errors split matches the in-process drivers."""

    def __init__(self, base_url: str, telemetry,
                 timeout_s: float = 30.0):
        from code2vec_tpu.serving.batcher import ServerOverloaded
        self._overloaded = ServerOverloaded
        self.base_url = base_url
        self.telemetry = telemetry
        self.timeout_s = timeout_s

    def predict_lines(self, lines, deadline_ms: float = None):
        body = {"lines": list(lines)}
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        req = urllib.request.Request(
            self.base_url + "/predict",
            data=json.dumps(body).encode("utf-8"), method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as r:
                return json.loads(r.read().decode("utf-8"))[
                    "predictions"]
        except urllib.error.HTTPError as e:
            detail = e.read().decode("utf-8", "replace")[:200]
            if e.code == 429:
                raise self._overloaded(f"shed by front-end: {detail}")
            raise RuntimeError(f"HTTP {e.code}: {detail}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests", type=int, default=256)
    ap.add_argument("--methods", type=int, default=1)
    ap.add_argument("--qps", type=float, default=100.0)
    ap.add_argument("--concurrency", type=int, default=16,
                    help="client-side HTTP worker cap")
    ap.add_argument("--arrivals", default="poisson",
                    choices=["fixed", "poisson"])
    ap.add_argument("--modulation", default="none",
                    choices=["none", "diurnal", "bursty"])
    ap.add_argument("--modulation_period_s", type=float, default=30.0)
    ap.add_argument("--hot_key_frac", type=float, default=0.25)
    ap.add_argument("--hot_keys", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--serve_batch_max", type=int, default=16)
    ap.add_argument("--serve_batch_timeout_ms", type=float,
                    default=2.0)
    ap.add_argument("--serve_queue_depth", type=int, default=128)
    ap.add_argument("--serve_deadline_ms", type=float, default=2000.0)
    ap.add_argument("--serve_cache_size", type=int, default=512)
    ap.add_argument("--round", type=int, default=None,
                    help="round number recorded in the capture "
                         "(default: parsed from --out)")
    ap.add_argument("--out", default="SERVING_r01.json")
    args = ap.parse_args(argv)

    from code2vec_tpu.config import Config
    from code2vec_tpu.data import preprocess as preprocess_mod
    from code2vec_tpu.models.jax_model import Code2VecModel
    from code2vec_tpu.obs import Telemetry
    from code2vec_tpu.serving import ReplicaPool, ServingFrontend

    # the loadgen synthetic-model recipe: tiny vocabs, random weights
    # (latency is shape-dependent, not value-dependent)
    workdir = tempfile.mkdtemp(prefix="serving_bench_")
    raw = os.path.join(workdir, "raw.txt")
    flat = [ln for req in loadgen.gen_corpus(64, 2, seed=7)
            for ln in req]
    with open(raw, "w", encoding="utf-8") as f:
        f.write("\n".join(flat) + "\n")
    prefix = os.path.join(workdir, "tiny")
    preprocess_mod.main([
        "--train_data", raw, "--val_data", raw, "--test_data", raw,
        "--max_contexts", "16", "--word_vocab_size", "1000",
        "--path_vocab_size", "1000", "--target_vocab_size", "1000",
        "--output_name", prefix])
    cfg = Config(MAX_CONTEXTS=16, MAX_TOKEN_VOCAB_SIZE=1000,
                 MAX_PATH_VOCAB_SIZE=1000, MAX_TARGET_VOCAB_SIZE=1000,
                 DEFAULT_EMBEDDINGS_SIZE=16, USE_BF16=False)
    cfg.train_data_path = prefix
    cfg.SERVE_BATCH_MAX = args.serve_batch_max
    cfg.SERVE_BATCH_TIMEOUT_MS = args.serve_batch_timeout_ms
    cfg.SERVE_QUEUE_DEPTH = args.serve_queue_depth
    cfg.SERVE_DEADLINE_MS = args.serve_deadline_ms
    cfg.SERVE_CACHE_SIZE = args.serve_cache_size
    cfg.SERVE_REPLICAS = args.replicas
    cfg.SERVE_MAX_REPLICAS = max(args.replicas,
                                 cfg.SERVE_MAX_REPLICAS)

    tele = Telemetry.memory("serving-bench").make_threadsafe()
    pool = ReplicaPool(cfg, lambda: Code2VecModel(cfg),
                       replicas=args.replicas, telemetry=tele).start()
    frontend = ServingFrontend(pool, port=0, telemetry=tele).start()
    base = f"http://127.0.0.1:{frontend.bound_port}"

    corpus = loadgen.gen_corpus(args.requests, args.methods,
                                max_ctx=min(cfg.MAX_CONTEXTS, 12))
    client = HttpPredictClient(base, tele)
    try:
        report = loadgen.run_load(
            client, corpus, mode="open",
            concurrency=args.concurrency, qps=args.qps,
            arrivals=args.arrivals,
            modulation=(None if args.modulation == "none"
                        else args.modulation),
            modulation_period_s=args.modulation_period_s,
            hot_key_frac=args.hot_key_frac, hot_keys=args.hot_keys,
            seed=args.seed)
        compile_delta = pool.compile_delta()
        pool_table = pool.pool_table()
    finally:
        frontend.stop()
        pool.close()

    rnd = args.round
    if rnd is None:
        import re
        m = re.search(r"r(\d+)", os.path.basename(args.out))
        rnd = int(m.group(1)) if m else 1
    capture = {
        "schema": "serving",
        "round": rnd,
        "serving_p99_ms": report["latency"]["p99_ms"],
        "serving_req_per_sec": report["throughput_rps"],
        "serving_p50_ms": report["latency"]["p50_ms"],
        "replicas": args.replicas,
        "offered_qps": args.qps,
        "arrivals": report["arrivals"],
        "modulation": report["modulation"],
        "hot_key_frac": args.hot_key_frac,
        "requests": report["requests"],
        "ok": report["ok"],
        "shed": report["shed"],
        "errors": report["errors"],
        "cache_hits": report["counters"].get("serve/cache_hit", 0),
        "new_compilations_under_load": compile_delta,
        "pool": {"size": pool_table["size"],
                 "ready": pool_table["ready"],
                 "generation": pool_table["generation"]},
    }
    text = json.dumps(capture, indent=2)
    print(text)
    with open(args.out, "w", encoding="utf-8") as f:
        f.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
