#!/usr/bin/env python3
"""Compiled-memory proof for ring attention's O(C/s) claim.

BASELINE.md's long-context section states the payoff of
ops/ring_attention.py: with the context dim sharded s ways, per-device
attention memory stays O(C/s), where the default XLA path all-gathers
K/V to O(C) per device. This tool makes that claim *measured* rather
than asserted: it compiles BOTH execution modes for the same global
shapes on the 8-device virtual CPU mesh (sharding semantics are
platform-independent — what XLA materializes per device is decided at
partitioning time, not by the backend) and reports each program's
per-device temp memory from `compiled.memory_analysis()`.

  python tools/ring_memory.py [--ctx 16384] [--batch 4] [--heads 8]
      [--head_dim 64] [--shards 8]

Prints one JSON line with temp bytes per device for ring vs all-gather
and the ratio.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ctx", type=int, default=16384)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head_dim", type=int, default=64)
    ap.add_argument("--shards", type=int, default=8)
    a = ap.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", max(a.shards, 1))
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from code2vec_tpu.ops.ring_attention import ring_attention
    from code2vec_tpu.parallel.mesh import CONTEXT_AXIS, make_mesh

    mesh = make_mesh(data=1, model=1, context=a.shards)
    B, H, C, hd = a.batch, a.heads, a.ctx, a.head_dim
    spec = P(None, None, CONTEXT_AXIS, None)
    shard = NamedSharding(mesh, spec)
    mask_shard = NamedSharding(mesh, P(None, CONTEXT_AXIS))

    def make_inputs():
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.device_put(
            jax.random.normal(k1, (B, H, C, hd), jnp.float32), shard)
        k = jax.device_put(
            jax.random.normal(k2, (B, H, C, hd), jnp.float32), shard)
        v = jax.device_put(
            jax.random.normal(k3, (B, H, C, hd), jnp.float32), shard)
        m = jax.device_put(jnp.zeros((B, C), jnp.float32), mask_shard)
        return q, k, v, m

    def dense(q, k, v, log_mask):
        # the non-ring path: plain attention math; with K/V sharded on
        # ctx, XLA's partitioner inserts the all-gather
        scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
        logits = (jnp.einsum("bhqd,bhkd->bhqk", q, k)
                  .astype(jnp.float32) * scale
                  + log_mask[:, None, None, :])
        w = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", w, v.astype(w.dtype)
                          ).astype(q.dtype)

    args = make_inputs()
    shardings = (shard, shard, shard, mask_shard)
    out_ring = jax.jit(
        lambda q, k, v, m: ring_attention(q, k, v, m, mesh),
        in_shardings=shardings, out_shardings=shard
    ).lower(*args).compile()
    out_dense = jax.jit(dense, in_shardings=shardings,
                        out_shardings=shard).lower(*args).compile()

    ring_tmp = out_ring.memory_analysis().temp_size_in_bytes
    dense_tmp = out_dense.memory_analysis().temp_size_in_bytes
    print(json.dumps({
        "metric": "attention_temp_bytes_per_device",
        "global_shape": [B, H, C, hd],
        "ctx_shards": a.shards,
        "ring_temp_bytes": int(ring_tmp),
        "allgather_temp_bytes": int(dense_tmp),
        "ratio_allgather_over_ring": round(dense_tmp
                                           / max(ring_tmp, 1), 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
