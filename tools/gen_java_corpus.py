#!/usr/bin/env python3
"""Generate a realistic synthetic Java corpus for the quality study.

BASELINE.md quality-evidence requirement (SURVEY.md §8.4 item 3): the
sampled-softmax / low-precision ablations need a corpus with a ≥50K-name
target vocabulary and realistic skew — the 8-class test fixture can't
show an F1 gap. This generator writes Java classes whose method names
are verb+adjective+noun subtoken compositions (Zipf-weighted, so name
frequencies look like real code) and whose bodies reference identifiers
correlated with the name — the actual signal code2vec learns. The
corpus goes through the NATIVE C++ extractor like any real dataset.

Usage:
  python tools/gen_java_corpus.py --out /tmp/qs/raw --names 50000 \
      --methods 250000 [--seed 7]
creates <out>/{train,val,test}/*.java
"""

from __future__ import annotations

import argparse
import os
import random

VERBS = ["get", "set", "is", "has", "compute", "find", "make", "build",
         "read", "write", "add", "remove", "update", "create", "delete",
         "load", "store", "parse", "format", "init", "reset", "clear",
         "count", "sum", "merge", "split", "copy", "move", "sort",
         "filter", "map", "apply", "check", "validate", "convert",
         "encode", "decode", "open", "close", "flush"]
ADJS = ["", "max", "min", "total", "last", "first", "next", "prev",
        "old", "new", "raw", "base", "temp", "local", "global", "cached",
        "active", "pending", "valid", "dirty", "sorted", "unique",
        "shared", "remote", "inner", "outer", "upper", "lower", "left",
        "right", "partial", "full", "empty", "default", "current",
        "initial", "final2", "safe", "fast", "slow"]
NOUNS = ["value", "name", "index", "count", "item", "node", "list",
         "map2", "key", "entry", "buffer", "stream", "file", "path",
         "user", "account", "session", "token", "request", "response",
         "message", "event", "handler", "state", "config", "option",
         "result", "error", "status", "code", "line", "column", "row",
         "cell", "table", "record", "field", "type", "size", "length",
         "width", "height", "offset", "position", "range", "limit",
         "total", "amount", "price", "rate", "score", "weight", "level",
         "depth", "degree", "angle", "point", "vector", "matrix",
         "color", "image", "pixel", "frame", "page", "block", "chunk",
         "segment", "region", "zone", "area", "bounds", "margin",
         "border", "padding", "label", "title", "text", "word", "char2",
         "digit", "number", "flag", "mask", "bit", "byte2", "hash",
         "checksum", "id2", "uuid", "version", "revision", "timestamp",
         "date", "time", "duration", "interval", "delay", "timeout",
         "retry", "attempt", "batch", "queue", "stack", "heap", "tree",
         "graph", "edge", "vertex", "parent", "child", "sibling",
         "root", "leaf", "branch", "head", "tail", "cursor", "iterator"]


def cap(s: str) -> str:
    return s[:1].upper() + s[1:] if s else s


def tail_name(rng: random.Random) -> str:
    """A random camelCase identifier from a combinatorially large space
    — the long-tail distractor-name universe of --tail_names mode."""
    syll = ["tmp", "buf", "acc", "cur", "aux", "raw", "alt", "seq",
            "loc", "ref", "arg", "ctx", "mem", "reg", "idx", "ptr",
            "len", "pos", "src", "dst", "obj", "rec", "seg", "blk"]
    k = rng.randint(2, 3)
    parts = [rng.choice(syll) for _ in range(k)]
    return parts[0] + "".join(cap(p) for p in parts[1:])


def method_source(rng: random.Random, verb: str, adj: str,
                  noun: str, tail_pool=None) -> str:
    """A method whose body references identifiers correlated with the
    name (the signal), plus random distractor statements (the noise).

    With `tail_pool` (a list of long-tail junk names, --tail_names
    mode), the body additionally declares 2-3 distractor locals drawn
    from the tail and REPEATS the signal through a second correlated
    local — the regime real code lives in: redundant naming cues plus
    a rare-name tail, where single-token renames are weaker and
    gradient-chosen replacements become frequency outliers
    (BASELINE.md "Adversarial robustness")."""
    field = (adj + cap(noun)) if adj else noun
    mname = verb + cap(adj) + cap(noun) if adj else verb + cap(noun)
    distract = rng.choice(NOUNS)
    d2 = rng.choice(NOUNS)
    lines = []
    if verb in ("get", "read", "load"):
        lines = [f"int {mname}() {{",
                 f"  return {field};", "}"]
    elif verb in ("set", "write", "store", "update"):
        lines = [f"void {mname}(int {field}) {{",
                 f"  this.{field} = {field};", "}"]
    elif verb in ("is", "has", "check", "validate"):
        lines = [f"boolean {mname}() {{",
                 f"  return {field} > 0;", "}"]
    elif verb in ("count", "sum"):
        lines = [f"int {mname}(int[] items) {{",
                 "  int total = 0;",
                 "  for (int i = 0; i < items.length; i++) {",
                 f"    total += items[i] * {field};", "  }",
                 "  return total;", "}"]
    elif verb in ("find",):
        lines = [f"int {mname}(int[] items) {{",
                 "  for (int i = 0; i < items.length; i++) {",
                 f"    if (items[i] == {field}) {{ return i; }}", "  }",
                 "  return -1;", "}"]
    elif verb in ("add", "merge"):
        lines = [f"int {mname}(int other) {{",
                 f"  {field} = {field} + other;",
                 f"  return {field};", "}"]
    elif verb in ("remove", "delete", "clear", "reset"):
        lines = [f"void {mname}() {{",
                 f"  {field} = 0;",
                 f"  int {distract} = 0;", "}"]
    else:
        lines = [f"int {mname}(int x) {{",
                 f"  int {field} = x * 2 + {d2};",
                 f"  if ({field} > x) {{ {field} -= 1; }}",
                 f"  return {field};", "}"]
    extra = ([f"  int {distract} = {d2} + 1;"]
             if rng.random() < 0.3 else [])
    if tail_pool:
        # tail mode inserts EVERYTHING before the last return statement
        # (javac-valid placement), junk names sampled WITHOUT
        # replacement (no duplicate locals)
        at = len(lines) - 1
        for idx in range(len(lines) - 1, -1, -1):
            if lines[idx].lstrip().startswith("return"):
                at = idx
                break
        extra += [f"  int {field}Copy = {field} + 0;"]
        extra += [f"  int {junk} = {rng.randrange(9)};"
                  for junk in rng.sample(tail_pool, rng.randint(2, 3))]
        lines[at:at] = extra
    else:
        # default mode keeps the historical before-brace placement —
        # it can land after a trailing return (extractor-only corpus;
        # javac-correctness is a tail-mode property), and moving it
        # would break the byte-identical-rebuild anchor the quality
        # study's reproducibility claim rests on
        for e in extra:
            lines.insert(-1, e)
    return "\n".join("  " + ln for ln in lines)


REDUNDANT_SUFFIXES = ("Src", "Buf", "Acc")  # one per cue position

# --deep_tail mode's identifier alphabet. 40 syllables -> 40^k names of
# k parts; deep_tail_name() encodes an integer index in little-endian
# base-40, so names are distinct BY CONSTRUCTION (no rejection sampling,
# any pool size) and subtoken-decompose into common short subtokens the
# way real Java locals do (`tmpBufAcc` -> tmp|buf|acc).
DT_SYLL = ["tmp", "buf", "acc", "cur", "aux", "raw", "alt", "seq",
           "loc", "ref", "arg", "ctx", "mem", "reg", "idx", "ptr",
           "len", "pos", "src", "dst", "obj", "rec", "seg", "blk",
           "cnt", "val", "itm", "nod", "lnk", "key", "qty", "sum",
           "avg", "tot", "rem", "div", "mul", "off", "cap", "dim"]


def deep_tail_name(i: int) -> str:
    """Distinct camelCase identifier for pool index `i` (injective:
    standard little-endian base-len(DT_SYLL) digit sequences)."""
    digits = []
    n = i
    while True:
        digits.append(n % len(DT_SYLL))
        n //= len(DT_SYLL)
        if n == 0:
            break
    parts = [DT_SYLL[d] for d in digits]
    return parts[0] + "".join(cap(p) for p in parts[1:])


class DeepTailJunk:
    """--deep_tail junk-identifier source (VERDICT r4 item 1: put the
    rarity detector in the regime the paper claims it works in — a
    java-large-shaped identifier pool with a deep Zipf tail).

    Two disjoint index ranges of the deep_tail_name() space:
      - a `zipf_head` of the first `head` names, drawn Zipf-weighted
        (`zipf_per_method` draws/method) — the common/mid-frequency
        junk mass every real corpus has;
      - an unbounded FRESH iterator starting at index `head`
        (`fresh_per_method` names/method, never reused) — every draw is
        a corpus singleton, which is what makes the train-token
        histogram's tail deep (~methods x fresh_per_method distinct
        once-seen tokens). The iterator keeps advancing through
        val/test generation, so held-out methods carry never-seen
        (OOV-at-eval) junk exactly like unseen real code does.
    """

    def __init__(self, head: int, fresh_per_method: int,
                 zipf_per_method: int):
        self.head = head
        self.fresh_per_method = fresh_per_method
        self.zipf_per_method = zipf_per_method
        self._next_fresh = head
        self._zipf_w = [1.0 / (r + 10) for r in range(head)]

    def names_for_method(self, rng: random.Random,
                         forbidden=()) -> list:
        # dedupe all draws against this method's other locals so the
        # emitted class stays javac-valid (no duplicate declarations):
        # rng.choices draws with replacement, fresh names at small
        # --deep_tail_head are single-syllable words overlapping NOUNS,
        # and the caller's forbidden set carries its other declarations
        out = []
        taken = set(forbidden)
        while len(out) < self.fresh_per_method:
            nm = deep_tail_name(self._next_fresh)
            self._next_fresh += 1
            if nm not in taken:
                out.append(nm)
                taken.add(nm)
        if self.head:
            for i in rng.choices(range(self.head), weights=self._zipf_w,
                                 k=self.zipf_per_method):
                nm = deep_tail_name(i)
                if nm not in taken:
                    out.append(nm)
                    taken.add(nm)
        return out


def method_source_redundant(rng: random.Random, verb: str, adj: str,
                            noun: str, k_cues: int,
                            junk: DeepTailJunk = None) -> str:
    """--redundant_cues mode (VERDICT r4 item 6, the defense positive
    control): the label is carried by `k_cues` DISTINCT local variables,
    each individually label-identifying (cue_i = methodName+suffix_i, a
    distinct vocab token whose subtokens spell the full label), chained
    so every cue appears in multiple path contexts. Renaming any single
    variable provably leaves k-1 intact cues — an information-theoretic
    guarantee the default corpus lacks (there one field token is the
    only cue, so one rename destroys the label signal and NO defense
    can win; BASELINE.md round-3 'corpus determinism' analysis)."""
    mname = verb + cap(adj) + cap(noun) if adj else verb + cap(noun)
    cues = [mname + REDUNDANT_SUFFIXES[i % len(REDUNDANT_SUFFIXES)]
            + (str(i // len(REDUNDANT_SUFFIXES)) if
               i >= len(REDUNDANT_SUFFIXES) else "")
            for i in range(k_cues)]
    distract = rng.choice(NOUNS)
    lines = [f"int {mname}(int x) {{",
             f"  int {cues[0]} = x + 1;"]
    for prev, cur in zip(cues, cues[1:]):
        lines.append(f"  int {cur} = {prev} * 2;")
    if rng.random() < 0.3:
        lines.append(f"  int {distract} = x - 1;")
    if junk is not None:
        # deep-tail junk locals, javac-valid placement before the
        # return; each is a USED local (chained into a dead sum) so the
        # extractor gives it multiple path contexts, like real code —
        # a declared-but-unread local would surface in fewer contexts
        # than the attack's rename target ever does. `forbidden` keeps
        # a junk draw from colliding with ANY other declaration in this
        # method (DT_SYLL composites overlap NOUNS words and the cue /
        # sum locals on rare draws)
        names = junk.names_for_method(
            rng, forbidden=(distract, distract + "Sum", mname, *cues))
        lines += [f"  int {nm} = x + {i};"
                  for i, nm in enumerate(names)]
        lines.append("  int " + distract + "Sum = "
                     + " + ".join(names) + ";")
    lines.append(f"  return {cues[-1]};")
    lines.append("}")
    return "\n".join("  " + ln for ln in lines)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--names", type=int, default=50_000)
    ap.add_argument("--methods", type=int, default=250_000)
    ap.add_argument("--methods_per_class", type=int, default=50)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--tail_names", type=int, default=0,
                    help="size of a long-tail distractor-name pool; "
                         "0 (default) keeps the original corpus "
                         "byte-identical")
    ap.add_argument("--redundant_cues", type=int, default=0,
                    help="k>=1: every method carries k independent "
                         "label-identifying locals (defense positive "
                         "control; see method_source_redundant). "
                         "0 (default) keeps the original bodies")
    ap.add_argument("--deep_tail_fresh", type=int, default=0,
                    help="java-large-shaped identifier pool (detection "
                         "regime, VERDICT r4 item 1): N never-reused "
                         "singleton junk locals per method (the deep "
                         "tail). Requires --redundant_cues")
    ap.add_argument("--deep_tail_zipf", type=int, default=1,
                    help="Zipf-weighted draws/method from the junk "
                         "head pool (common junk mass); active only "
                         "with --deep_tail_fresh")
    ap.add_argument("--deep_tail_head", type=int, default=50_000,
                    help="size of the Zipf-weighted junk head pool")
    args = ap.parse_args()
    if args.deep_tail_fresh and not args.redundant_cues:
        ap.error("--deep_tail_fresh requires --redundant_cues (the "
                 "detection-regime corpus must not be single-token-"
                 "determined, or no defense/detection can win)")
    junk = (DeepTailJunk(args.deep_tail_head, args.deep_tail_fresh,
                         args.deep_tail_zipf)
            if args.deep_tail_fresh else None)
    rng = random.Random(args.seed)
    tail_pool = None
    if args.tail_names:
        tail_rng = random.Random(args.seed ^ 0x7A11)  # own stream:
        # the default (tail_names=0) rng sequence stays untouched.
        # dict.fromkeys: dedupe in generation order (a set's iteration
        # order varies with hash randomization -> nondeterministic pool)
        seen = dict.fromkeys(())
        attempts = 0
        while len(seen) < args.tail_names and \
                attempts < args.tail_names * 200:
            seen.setdefault(tail_name(tail_rng))
            attempts += 1
        if len(seen) < args.tail_names:
            ap.error(f"--tail_names {args.tail_names} exceeds the "
                     f"reachable name space (~14400; got {len(seen)})")
        tail_pool = list(seen)

    # build the name universe and give it a Zipf weighting
    combos = [(v, a, n) for v in VERBS for a in ADJS for n in NOUNS]
    rng.shuffle(combos)
    names = combos[:args.names]
    weights = [1.0 / (r + 10) for r in range(len(names))]  # Zipf-ish

    splits = (("train", 0.8), ("val", 0.1), ("test", 0.1))
    total_written = 0
    for split, frac in splits:
        n_methods = int(args.methods * frac)
        d = os.path.join(args.out, split)
        os.makedirs(d, exist_ok=True)
        # train: guarantee every name appears >=2 times (so the full
        # target vocab exists and is learnable), then fill the rest with
        # the Zipf draw; val/test: natural Zipf draw only.
        pool = []
        if split == "train":
            pool = [nm for nm in names for _ in range(2)]
            rng.shuffle(pool)
            pool = pool[:n_methods]
        pool += rng.choices(names, weights=weights,
                            k=n_methods - len(pool))
        rng.shuffle(pool)
        file_idx = 0
        written = 0
        while written < n_methods:
            k = min(args.methods_per_class, n_methods - written)
            chosen = pool[written:written + k]
            body = []
            fields = set()
            for v, a, n in chosen:
                if args.redundant_cues:
                    body.append(method_source_redundant(
                        rng, v, a, n, args.redundant_cues, junk=junk))
                else:
                    fields.add((a + cap(n)) if a else n)
                    body.append(method_source(rng, v, a, n,
                                              tail_pool=tail_pool))
            field_decls = "\n".join(f"  int {f};" for f in sorted(fields))
            cls = (f"class C{split.capitalize()}{file_idx} {{\n"
                   f"{field_decls}\n" + "\n".join(body) + "\n}\n")
            with open(os.path.join(d, f"C{file_idx}.java"), "w") as f:
                f.write(cls)
            file_idx += 1
            written += k
        total_written += written
        print(f"{split}: {written} methods in {file_idx} files")
    print(f"total: {total_written} methods, "
          f"{len(names)} distinct target names")
    if junk is not None:
        print(f"deep tail: {junk._next_fresh - junk.head} fresh "
              f"singleton junk names + {junk.head} Zipf-head junk "
              f"names across all splits")


if __name__ == "__main__":
    main()
