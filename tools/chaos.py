#!/usr/bin/env python3
"""Chaos scenario runner (ISSUE 10): exercise the detect -> decide ->
recover loop end to end, deterministically, on the CPU harness.

Each scenario builds a tiny synthetic dataset, runs REAL
`code2vec.py` training processes under the REAL supervisor
(training/supervisor.py) with a `--faults` spec arming the relevant
failpoint, and asserts the recovery contract:

  kill_resume        SIGKILL the (1-process) training run mid-epoch
                     under constant LR; the supervisor relaunches it
                     with --auto_resume and the final checkpoint is
                     BIT-IDENTICAL to an uninterrupted run's — the
                     step-keyed rng + resumed shuffle stream replay
                     the exact trajectory (the chaos-parity
                     acceptance). Tier-1 smoke: tests/test_chaos.py.
  kill_resume_2proc  Same contract through the 2-process Gloo cohort:
                     SIGKILL worker 1 mid-epoch, the supervisor
                     detects the dead peer, reaps the survivor, and
                     relaunches the WHOLE cohort coherently on a
                     fresh port (slow-marked test).
  corrupt_checkpoint Bit-flip a leaf blob in the latest committed
                     step; the supervisor's pre-launch verification
                     detects it, QUARANTINES the step dir, emits an
                     `alert` event through the alert engine, and the
                     run resumes from the prior committed step.
  serve_swap_kill    The serving-plane acceptance (ISSUE 18): under
                     open-loop Poisson load against a replica pool, a
                     replica dies mid-request (`serve/kill`, action
                     raise), a VERIFIED committed checkpoint hot-swaps
                     in one replica at a time, and a bit-flipped step
                     is REFUSED (ticket alert) — while p99 holds the
                     SLO, zero requests are lost, and zero new jit
                     compilations happen under load.
  kill_resize        The elastic-resume parity bar (ISSUE 13): SIGKILL
                     one peer of a 2-process cohort mid-epoch; the
                     supervisor (resize_policy=shrink) RE-FORMS the
                     cohort at 1 process instead of relaunching the
                     world — zero full-cohort relaunches — the
                     checkpoint layer reshards the restore onto the
                     new mesh, and the finished run's params are
                     BIT-IDENTICAL to an uninterrupted 1-process run
                     resumed from the same committed step (constant
                     LR). Also measures recovery cost
                     (recovery_steps_lost, recovery_seconds — the
                     multichip bench's kill-mid-run leg reuses the
                     run half of this scenario).

Usage (repo root):

  python tools/chaos.py --list
  python tools/chaos.py kill_resume --out /tmp/chaos
  python tools/chaos.py corrupt_checkpoint --out /tmp/chaos

Prints a JSON result per scenario; exit 0 = contract held, 1 = it did
not. The fault markers make every kill a cross-restart once-latch, so
a scenario is a TEST, not a dice roll.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# tiny-but-learnable synthetic corpus (the tests/helpers.py shape,
# re-stated here so a TOOL does not import the test tree)
_TOKENS = ["foo", "bar", "baz", "qux", "value", "name", "index", "count"]
_PATHS = [str(h) for h in (123456, -98765, 424242, 1337, -777, 31415)]
_TARGETS = ["get|value", "set|value", "get|name", "set|name",
            "add|item", "remove|item", "to|string", "is|empty"]


def _raw_lines(n: int, seed: int, max_ctx: int) -> list:
    rng = random.Random(seed)
    lines = []
    for _ in range(n):
        t = rng.randrange(len(_TARGETS))
        ctxs = []
        for _ in range(rng.randint(1, max_ctx)):
            a = _TOKENS[(t + rng.randrange(2)) % len(_TOKENS)]
            b = _TOKENS[(t * 3 + rng.randrange(2)) % len(_TOKENS)]
            p = _PATHS[t % len(_PATHS)] if rng.random() < 0.7 \
                else rng.choice(_PATHS)
            ctxs.append(f"{a},{p},{b}")
        lines.append(_TARGETS[t] + " " + " ".join(ctxs))
    return lines


def build_dataset(out_dir: str, *, n_train: int = 96,
                  max_contexts: int = 8) -> str:
    from code2vec_tpu.data import preprocess as preprocess_mod
    os.makedirs(out_dir, exist_ok=True)
    paths = {}
    for split, n, seed in (("train", n_train, 1), ("val", 16, 2),
                           ("test", 16, 3)):
        p = os.path.join(out_dir, f"raw.{split}.txt")
        with open(p, "w", encoding="utf-8") as f:
            f.write("\n".join(_raw_lines(n, seed, max_contexts)) + "\n")
        paths[split] = p
    prefix = os.path.join(out_dir, "chaos")
    preprocess_mod.main([
        "--train_data", paths["train"], "--val_data", paths["val"],
        "--test_data", paths["test"],
        "--max_contexts", str(max_contexts),
        "--word_vocab_size", "1000", "--path_vocab_size", "1000",
        "--target_vocab_size", "1000", "--output_name", prefix])
    return prefix


def train_cmd(prefix: str, save_dir: str, *, epochs: int,
              batch: int = 32, max_contexts: int = 8) -> list:
    """Constant LR (the parity acceptance's requirement: a resumed
    cosine horizon would legitimately diverge) over the tiny corpus;
    everything else is the shipped default — async checkpointing
    included."""
    return [sys.executable, os.path.join(_REPO, "code2vec.py"),
            "--data", prefix, "--save", save_dir,
            "--epochs", str(epochs), "--batch_size", str(batch),
            "--max_contexts", str(max_contexts),
            "--lr_schedule", "constant", "--seed", "11"]


def _run_plain(cmd: list, *, cpu_devices: int, timeout_s: float) -> None:
    from code2vec_tpu.parallel.compat import cpu_worker_env
    r = subprocess.run(cmd, env=cpu_worker_env(cpu_devices),
                       stdout=subprocess.PIPE,
                       stderr=subprocess.STDOUT, text=True,
                       timeout=timeout_s)
    if r.returncode != 0:
        raise RuntimeError(f"oracle run failed (rc {r.returncode}):\n"
                           f"{r.stdout[-4000:]}")


def _latest_state(ckpt_dir: str):
    """Restore the latest committed step onto THIS process's first
    device, template built from orbax metadata: a cohort-saved
    checkpoint carries distributed device ids its saver owned, so a
    template-free restore here would refuse — explicit single-device
    shardings reshard it instead (the cross-topology restore the
    checkpoint layer already promises)."""
    import jax
    import numpy as np
    import orbax.checkpoint as ocp

    from code2vec_tpu.training import checkpoint as ckpt
    step = ckpt.latest_step(ckpt_dir)
    assert step is not None, f"no committed checkpoint under {ckpt_dir}"
    path = os.path.abspath(
        os.path.join(ckpt_dir, f"step_{step}", "state"))
    sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    with ocp.StandardCheckpointer() as c:
        meta = c.metadata(path)
        def leaf_template(m):
            if m.shape:
                return jax.ShapeDtypeStruct(m.shape, m.dtype,
                                            sharding=sharding)
            # scalar leaves (step, optimizer counts) restore as plain
            # python scalars — numpy scalars are not a supported
            # template type
            return 0 if np.issubdtype(m.dtype, np.integer) else 0.0

        template = jax.tree_util.tree_map(leaf_template, meta)
        restored = c.restore(path, template)
    return step, jax.tree_util.tree_map(
        lambda x: np.asarray(x) if hasattr(x, "shape") else x,
        restored)


def trees_bit_equal(a, b) -> list:
    """Leaf paths that DIFFER between two restored pytrees (empty =
    bit-identical)."""
    import jax
    import numpy as np
    la = jax.tree_util.tree_flatten_with_path(a)[0]
    lb = jax.tree_util.tree_flatten_with_path(b)[0]
    diffs = []
    if len(la) != len(lb):
        return ["<structure mismatch>"]
    for (ka, va), (kb, vb) in zip(la, lb):
        if ka != kb:
            diffs.append(f"<key {ka} vs {kb}>")
        elif not np.array_equal(np.asarray(va), np.asarray(vb)):
            diffs.append(jax.tree_util.keystr(ka))
    return diffs


def _write_faults(path: str, sites: dict) -> str:
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"seed": 0, "sites": sites}, f)
    return path


def _supervised(child_cmd: list, *, out: str, num_procs: int = 1,
                cpu_devices: int = 1, max_restarts: int = 2,
                ckpt_dir: str, telemetry_dir: str | None = None,
                attempt_timeout_s: float = 600.0, **sup_kwargs):
    from code2vec_tpu.obs import Telemetry
    from code2vec_tpu.resilience.retry import RetryPolicy
    from code2vec_tpu.training.supervisor import (Supervisor,
                                                  build_cli_spawn)

    def log(msg: str) -> None:
        print(f"[chaos] {msg}", flush=True)

    telemetry = Telemetry.create(telemetry_dir, component="supervisor",
                                 log=log) if telemetry_dir else None
    sup = Supervisor(
        build_cli_spawn(child_cmd, num_procs=num_procs,
                        out_dir=os.path.join(out, "logs"),
                        cpu_devices=cpu_devices, log=log),
        num_procs=num_procs, max_restarts=max_restarts,
        ckpt_dir=ckpt_dir, telemetry=telemetry, log=log,
        peer_grace_s=10.0, attempt_timeout_s=attempt_timeout_s,
        backoff=RetryPolicy("supervisor-restart", max_attempts=1,
                            base_delay_s=0.2, max_delay_s=1.0,
                            seed=0), **sup_kwargs)
    try:
        rc = sup.run()
    finally:
        # flush even when the budget exhausts — the supervisor JSONL
        # is the postmortem for exactly that case
        if telemetry is not None:
            telemetry.close()
    return rc, sup, telemetry.run_dir if telemetry is not None else None


def _read_events(run_dir: str) -> list:
    out = []
    with open(os.path.join(run_dir, "events.jsonl"),
              encoding="utf-8") as f:
        for ln in f:
            if ln.strip():
                out.append(json.loads(ln))
    return out


# ------------------------------------------------------------ scenarios

def scenario_kill_resume(out: str, *, epochs: int = 2,
                         kill_at_step: int = 5) -> dict:
    """SIGKILL mid-epoch (1 process) -> supervisor relaunch ->
    auto-resume -> final checkpoint bit-identical to an uninterrupted
    run's."""
    prefix = build_dataset(os.path.join(out, "data"))
    oracle_dir = os.path.join(out, "ckpt_oracle")
    chaos_dir = os.path.join(out, "ckpt_chaos")
    t0 = time.time()
    _run_plain(train_cmd(prefix, oracle_dir, epochs=epochs),
               cpu_devices=1, timeout_s=600)

    marker = os.path.join(out, "killed.once")
    faults = _write_faults(os.path.join(out, "faults.json"), {
        "train/kill": {"action": "kill", "at": kill_at_step,
                       "marker": marker}})
    cmd = train_cmd(prefix, chaos_dir, epochs=epochs) \
        + ["--auto_resume", "--faults", faults]
    rc, sup, run_dir = _supervised(
        cmd, out=out, ckpt_dir=chaos_dir,
        telemetry_dir=os.path.join(out, "tele"))

    o_step, o_state = _latest_state(oracle_dir)
    c_step, c_state = _latest_state(chaos_dir)
    diffs = trees_bit_equal(o_state, c_state)
    result = {
        "scenario": "kill_resume",
        "kill_fired": os.path.exists(marker),
        "supervisor_rc": rc,
        "restarts": sup.restarts,
        "resumed_from_step": sup.resumed_from_step,
        "oracle_step": o_step, "chaos_step": c_step,
        "param_diffs": diffs,
        "wall_s": round(time.time() - t0, 1),
        "telemetry_run_dir": run_dir,
    }
    result["ok"] = (result["kill_fired"] and rc == 0
                    and sup.restarts == 1 and o_step == c_step
                    and not diffs)
    return result


def scenario_kill_resume_2proc(out: str, *, epochs: int = 3,
                               kill_at_step: int = 4) -> dict:
    """The same parity contract through a REAL 2-process Gloo cohort:
    worker 1 is SIGKILLed mid-epoch; the supervisor reaps the
    surviving peer and relaunches the cohort coherently on a fresh
    port."""
    prefix = build_dataset(os.path.join(out, "data"))
    oracle_dir = os.path.join(out, "ckpt_oracle")
    chaos_dir = os.path.join(out, "ckpt_chaos")
    t0 = time.time()
    # the oracle is ALSO a 2-process supervised run: identical
    # topology, the only difference is the injected fault. The Gloo
    # loopback transport race can restart the ORACLE too (its child
    # has --auto_resume appended just like any supervised run) — that
    # is fine precisely BECAUSE resume is bit-exact, which is the
    # property under test; oracle restarts are recorded, not rejected.
    rc_o, sup_o, _ = _supervised(
        train_cmd(prefix, oracle_dir, epochs=epochs)
        + ["--auto_resume"],
        out=os.path.join(out, "oracle"), num_procs=2, cpu_devices=2,
        ckpt_dir=oracle_dir)
    if rc_o != 0:
        return {"scenario": "kill_resume_2proc", "ok": False,
                "error": f"oracle cohort failed (rc {rc_o}, "
                         f"restarts {sup_o.restarts})"}

    marker = os.path.join(out, "killed.once")
    faults = _write_faults(os.path.join(out, "faults.json"), {
        "train/kill": {"action": "kill", "at": kill_at_step,
                       "process": 1, "marker": marker}})
    cmd = train_cmd(prefix, chaos_dir, epochs=epochs) \
        + ["--auto_resume", "--faults", faults]
    rc, sup, run_dir = _supervised(
        cmd, out=os.path.join(out, "chaos"), num_procs=2,
        cpu_devices=2, ckpt_dir=chaos_dir,
        telemetry_dir=os.path.join(out, "tele"))

    o_step, o_state = _latest_state(oracle_dir)
    c_step, c_state = _latest_state(chaos_dir)
    diffs = trees_bit_equal(o_state, c_state)
    result = {
        "scenario": "kill_resume_2proc",
        "kill_fired": os.path.exists(marker),
        "supervisor_rc": rc,
        "oracle_restarts": sup_o.restarts,
        "restarts": sup.restarts,
        "resumed_from_step": sup.resumed_from_step,
        "oracle_step": o_step, "chaos_step": c_step,
        "param_diffs": diffs,
        "wall_s": round(time.time() - t0, 1),
        "telemetry_run_dir": run_dir,
    }
    result["ok"] = (result["kill_fired"] and rc == 0
                    and sup.restarts >= 1 and o_step == c_step
                    and not diffs)
    return result


def _step_event_times(tele_root: str) -> list:
    """(ts, step) for every per-step telemetry event under any run dir
    of `tele_root`. JSONL is flushed per event, so even a SIGKILLed
    attempt's steps are on disk up to the kill."""
    import glob as glob_mod
    out = []
    for path in glob_mod.glob(os.path.join(tele_root, "*",
                                           "events.jsonl")):
        with open(path, encoding="utf-8") as f:
            for ln in f:
                if not ln.strip():
                    continue
                ev = json.loads(ln)
                if ev.get("kind") == "step":
                    out.append((float(ev["ts"]), int(ev["step"])))
    return sorted(out)


def _marker_ts(marker: str) -> float | None:
    """The firing wall-clock the fault site wrote into its once-latch
    marker (`... ts=<float>`)."""
    import re as re_mod
    try:
        with open(marker, encoding="utf-8") as f:
            m = re_mod.search(r"ts=([0-9.]+)", f.read())
        return float(m.group(1)) if m else None
    except OSError:
        return None


def run_kill_resize(out: str, *, epochs: int = 3, kill_at_step: int = 4,
                    procs: int = 2, cpu_devices: int = 2,
                    timeout_s: float = 600.0, tries: int = 3) -> dict:
    """The run half of the kill_resize scenario, reused by
    tools/multichip_bench.py's kill-mid-run leg: train a `procs`-process
    cohort under the shrink-policy supervisor, SIGKILL worker 1 at
    `kill_at_step`, let the cohort RE-FORM at procs−1, and measure the
    recovery cost — steps lost (kill step minus the committed step the
    re-formed cohort resumed from) and seconds from the kill to the
    first post-resize training step (per-step telemetry events from the
    relaunched children, against the kill timestamp the fault marker
    recorded).

    The CPU harness's loopback-Gloo transport race (the compat
    docstring's `op.preamble.length <= op.nbytes` crash) can abort a
    cohort at startup BEFORE the injected kill arms — the supervisor
    handles it per its policy (a lone early death resizes, a
    simultaneous whole-cohort crash relaunches full size as
    `cohort_failure`), but as a measurement such a try is transient
    infra, not the contract: it is retried in a fresh subdir (the
    multichip_bench pair-retry discipline) until the kill actually
    fired after a committed checkpoint existed."""
    last = None
    for i in range(max(1, tries)):
        sub = os.path.join(out, f"try{i}")
        os.makedirs(sub, exist_ok=True)
        last = _run_kill_resize_once(
            sub, epochs=epochs, kill_at_step=kill_at_step,
            procs=procs, cpu_devices=cpu_devices, timeout_s=timeout_s)
        if (last["kill_fired"] and last["supervisor_rc"] == 0
                and last["resumed_from_step"] is not None):
            return last
        print(f"[chaos] kill_resize try {i} hit transient infra "
              f"(kill_fired={last['kill_fired']}, resumed="
              f"{last['resumed_from_step']}); retrying in a fresh dir",
              flush=True)
    return last


def _run_kill_resize_once(out: str, *, epochs: int, kill_at_step: int,
                          procs: int, cpu_devices: int,
                          timeout_s: float) -> dict:
    prefix = build_dataset(os.path.join(out, "data"))
    chaos_dir = os.path.join(out, "ckpt_chaos")
    child_tele = os.path.join(out, "child_tele")
    marker = os.path.join(out, "killed.once")
    faults = _write_faults(os.path.join(out, "faults.json"), {
        "train/kill": {"action": "kill", "at": kill_at_step,
                       "process": 1, "marker": marker}})
    # sync checkpointing: the contract under test is TOPOLOGY recovery
    # from a committed step, so the committed step must be
    # deterministic — on this harness post-compile steps run ~20 ms
    # while the 2-process collective async commit takes hundreds, so a
    # mid-epoch kill would race (and essentially always beat) the
    # boundary save. The mid-ASYNC-save kill discipline for fixed
    # cohorts is kill_resume's job (shipped defaults there).
    cmd = train_cmd(prefix, chaos_dir, epochs=epochs) \
        + ["--async_checkpoint", "off",
           "--auto_resume", "--faults", faults,
           "--telemetry_dir", child_tele]
    rc, sup, run_dir = _supervised(
        cmd, out=out, num_procs=procs, cpu_devices=cpu_devices,
        ckpt_dir=chaos_dir, telemetry_dir=os.path.join(out, "tele"),
        attempt_timeout_s=timeout_s,
        resize_policy="shrink", min_procs=1)

    kill_ts = _marker_ts(marker)
    resumed = sup.resumed_from_step
    steps = _step_event_times(child_tele)
    first_post = next((ts for ts, _s in steps
                       if sup.last_launch_ts is not None
                       and ts >= sup.last_launch_ts), None)
    recovery_seconds = (round(first_post - kill_ts, 3)
                        if first_post is not None
                        and kill_ts is not None else None)
    recovery_steps_lost = (kill_at_step - resumed
                           if resumed is not None else kill_at_step)
    return {
        "kill_fired": os.path.exists(marker),
        "supervisor_rc": rc,
        "restarts": sup.restarts,
        "resizes": [list(r) for r in sup.resizes],
        "full_relaunches": sup.full_relaunches,
        "cohort_size_final": sup.cur_procs,
        "resumed_from_step": resumed,
        "kill_at_step": kill_at_step,
        "recovery_steps_lost": recovery_steps_lost,
        "recovery_seconds": recovery_seconds,
        "data_prefix": prefix,
        "ckpt_dir": chaos_dir,
        "telemetry_run_dir": run_dir,
    }


def scenario_kill_resize(out: str, *, epochs: int = 3,
                         kill_at_step: int = 4) -> dict:
    """SIGKILL one peer of a 2-process cohort mid-epoch; the supervisor
    re-forms the mesh at 1 process (a resize, ZERO full-cohort
    relaunches), the checkpoint reshards onto the survivor, and the
    final params are bit-identical to an uninterrupted 1-process run
    resumed from the same committed step (constant LR) — the elastic
    resume parity bar (ISSUE 13)."""
    import shutil
    t0 = time.time()
    run = run_kill_resize(out, epochs=epochs,
                          kill_at_step=kill_at_step)
    result = dict(run, scenario="kill_resize",
                  wall_s=None, param_diffs=["<not compared>"])
    chaos_dir = run["ckpt_dir"]
    S = run["resumed_from_step"]
    if run["supervisor_rc"] != 0 or S is None:
        result["ok"] = False
        result["wall_s"] = round(time.time() - t0, 1)
        return result

    # the oracle: an UNINTERRUPTED 1-process run resumed from the SAME
    # committed step the re-formed cohort restored — committed step
    # dirs are immutable, so the chaos dir still holds the exact bytes
    oracle_dir = os.path.join(out, "ckpt_oracle")
    os.makedirs(oracle_dir)
    shutil.copytree(os.path.join(chaos_dir, f"step_{S}"),
                    os.path.join(oracle_dir, f"step_{S}"))
    for sidecar in ("manifest.json", "vocab.pkl"):
        shutil.copy(os.path.join(chaos_dir, sidecar),
                    os.path.join(oracle_dir, sidecar))
    # cpu_devices + checkpoint mode match the re-formed chaos child
    # (1 process x 2 virtual devices, sync saves) so the two runs
    # differ in NOTHING but history
    _run_plain(train_cmd(run["data_prefix"], oracle_dir, epochs=epochs)
               + ["--async_checkpoint", "off", "--auto_resume"],
               cpu_devices=2, timeout_s=600)

    o_step, o_state = _latest_state(oracle_dir)
    c_step, c_state = _latest_state(chaos_dir)
    diffs = trees_bit_equal(o_state, c_state)
    result.update(
        oracle_step=o_step, chaos_step=c_step, param_diffs=diffs,
        wall_s=round(time.time() - t0, 1))
    result["ok"] = (run["kill_fired"] and run["supervisor_rc"] == 0
                    and run["restarts"] == 1
                    and run["resizes"] == [[2, 1]]
                    and run["full_relaunches"] == 0
                    and o_step == c_step and not diffs)
    return result


def _flip_byte_in_largest_blob(step_dir: str) -> str:
    """Flip one byte mid-file in the largest file of the committed
    state tree — the bit-rot the checksums exist to catch."""
    state = os.path.join(step_dir, "state")
    largest, size = None, -1
    for base, _dirs, files in os.walk(state):
        for name in files:
            p = os.path.join(base, name)
            s = os.path.getsize(p)
            if s > size:
                largest, size = p, s
    assert largest is not None and size > 0
    with open(largest, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    return largest


def scenario_corrupt_checkpoint(out: str) -> dict:
    """Bit-flip a leaf blob in the latest committed step: verified
    restore detects it, the supervisor quarantines the step dir, emits
    an `alert` event, and training resumes from the prior committed
    step."""
    from code2vec_tpu.training import checkpoint as ckpt
    prefix = build_dataset(os.path.join(out, "data"))
    ckpt_dir = os.path.join(out, "ckpt")
    t0 = time.time()
    # 2 epochs -> two committed, checksummed steps (3 and 6)
    _run_plain(train_cmd(prefix, ckpt_dir, epochs=2),
               cpu_devices=1, timeout_s=600)
    steps = sorted(s for s, _ in ckpt._step_dirs(ckpt_dir))
    assert len(steps) == 2, steps
    flipped = _flip_byte_in_largest_blob(
        os.path.join(ckpt_dir, f"step_{steps[-1]}"))

    # resume for a 3rd epoch: the supervisor must fall back to steps[0]
    cmd = train_cmd(prefix, ckpt_dir, epochs=3) + ["--auto_resume"]
    rc, sup, run_dir = _supervised(
        cmd, out=out, ckpt_dir=ckpt_dir,
        telemetry_dir=os.path.join(out, "tele"))

    quarantined = os.path.join(ckpt_dir, ckpt.QUARANTINE_DIRNAME,
                               f"step_{steps[-1]}")
    alerts = [e for e in _read_events(run_dir)
              if e.get("kind") == "alert"
              and e.get("rule") == "checkpoint_quarantined"
              and e.get("transition") == "firing"] if run_dir else []
    final = ckpt.latest_step(ckpt_dir)
    result = {
        "scenario": "corrupt_checkpoint",
        "flipped_file": os.path.relpath(flipped, out),
        "supervisor_rc": rc,
        "restarts": sup.restarts,
        "resumed_from_step": sup.resumed_from_step,
        "quarantined": sup.quarantined,
        "quarantine_dir_exists": os.path.isdir(quarantined),
        "alert_events": len(alerts),
        "final_step": final,
        "wall_s": round(time.time() - t0, 1),
        "telemetry_run_dir": run_dir,
    }
    result["ok"] = (rc == 0 and result["quarantine_dir_exists"]
                    and sup.resumed_from_step == steps[0]
                    and len(alerts) == 1
                    and final is not None and final > steps[-1])
    return result


def scenario_serve_swap_kill(out: str, *, replicas: int = 2,
                             requests: int = 768, qps: float = 120.0,
                             kill_at: int = 40) -> dict:
    """The serving-plane acceptance (ISSUE 18): a replica pool under
    open-loop Poisson load with hot-key skew takes a mid-request
    replica death (`serve/kill`), a rolling hot swap of a VERIFIED
    committed checkpoint, and a REFUSED bit-flipped step — and the
    external contract holds: p99 under the SLO, zero requests lost
    (sheds are explicit), zero new jit compilations under load, pool
    back to full strength."""
    import threading

    from code2vec_tpu.config import Config
    from code2vec_tpu.data import preprocess as preprocess_mod
    from code2vec_tpu.models.jax_model import Code2VecModel
    from code2vec_tpu.obs import Telemetry
    from code2vec_tpu.obs.alerts import AlertEngine, serving_slo_rules
    from code2vec_tpu.resilience import faults
    from code2vec_tpu.serving import ReloadManager, ReplicaPool
    from code2vec_tpu.training import checkpoint as ckpt
    from tools import loadgen

    t0 = time.time()
    # the loadgen tiny-model recipe: latency is shape-dependent, not
    # value-dependent, so random weights over tiny vocabs serve fine
    data_dir = os.path.join(out, "data")
    os.makedirs(data_dir, exist_ok=True)
    raw = os.path.join(data_dir, "raw.txt")
    with open(raw, "w", encoding="utf-8") as f:
        f.write("\n".join(ln for req in loadgen.gen_corpus(64, 2, seed=7)
                          for ln in req) + "\n")
    prefix = os.path.join(data_dir, "tiny")
    preprocess_mod.main([
        "--train_data", raw, "--val_data", raw, "--test_data", raw,
        "--max_contexts", "16", "--word_vocab_size", "1000",
        "--path_vocab_size", "1000", "--target_vocab_size", "1000",
        "--output_name", prefix])
    cfg = Config(MAX_CONTEXTS=16, MAX_TOKEN_VOCAB_SIZE=1000,
                 MAX_PATH_VOCAB_SIZE=1000, MAX_TARGET_VOCAB_SIZE=1000,
                 DEFAULT_EMBEDDINGS_SIZE=16, USE_BF16=False)
    cfg.train_data_path = prefix
    cfg.SERVE_REPLICAS = replicas
    cfg.SERVE_MAX_REPLICAS = max(replicas, cfg.SERVE_MAX_REPLICAS)

    # one in-band kill: the kill_at-th predict_lines call raises
    # FaultInjected inside whichever replica serves it (action "kill"
    # would SIGKILL this whole process) — the pool must retry the
    # request on a survivor and refill in the background
    faults.install({"seed": 0, "sites": {
        "serve/kill": {"action": "raise", "at": kill_at}}},
        log=lambda m: print(f"[chaos] {m}", flush=True))

    tele = Telemetry.memory("chaos-serving").make_threadsafe()
    pool = ReplicaPool(cfg, lambda: Code2VecModel(cfg),
                       replicas=replicas, telemetry=tele).start()
    alerts = AlertEngine.create(
        tele, mode="warn", rules=serving_slo_rules(cfg.SERVE_SLO_MS))
    reload_dir = os.path.join(out, "serve_ckpt")
    rm = ReloadManager(reload_dir, pool, telemetry=tele, alerts=alerts,
                       poll_s=0.1).start()

    progress = {}

    def _chaos_actions() -> None:
        import jax
        # vocabs/dims for the sidecars come from a live replica; the
        # swapped-in params are a real value change (same shapes, so
        # the swap must not recompile anything)
        model = pool._replicas[0].server.model
        new_params = jax.tree_util.tree_map(
            lambda x: (x * 1.001).astype(x.dtype),
            pool.params_template())
        time.sleep(0.5)  # let the load establish itself first
        ckpt.save_checkpoint(reload_dir, {"params": new_params}, 1,
                             model.vocabs, model.dims)
        deadline = time.time() + 60
        while rm.last_step < 1 and time.time() < deadline:
            time.sleep(0.05)
        if rm.last_step >= 1:
            progress["swap_ts"] = time.time()
        ckpt.save_checkpoint(reload_dir, {"params": new_params}, 2,
                             model.vocabs, model.dims)
        _flip_byte_in_largest_blob(os.path.join(reload_dir, "step_2"))
        deadline = time.time() + 60
        while 2 not in rm.refused and time.time() < deadline:
            time.sleep(0.05)
        if 2 in rm.refused:
            progress["refused_ts"] = time.time()

    actions = threading.Thread(target=_chaos_actions,
                               name="chaos-actions", daemon=True)
    corpus = loadgen.gen_corpus(requests, 1,
                                max_ctx=min(cfg.MAX_CONTEXTS, 12))
    try:
        actions.start()
        report = loadgen.run_load(
            pool, corpus, mode="open", concurrency=16, qps=qps,
            arrivals="poisson", hot_key_frac=0.25, hot_keys=8, seed=0)
        t_load_end = time.time()
        actions.join(timeout=120)
        # the refill may still be warming when the load drains; it
        # must land (back to full strength) before the verdict
        pool.wait_ready(replicas, timeout_s=120)
        compile_delta = pool.compile_delta()
        table = pool.pool_table()
        counters = dict(tele.counters)
        fired = faults.stats().get("serve/kill", {}).get("fired", 0)
        refused_state = next(
            (r["state"] for r in alerts.status_table()
             if r["rule"] == "reload_refused"), None)
    finally:
        rm.stop()
        pool.close()
        faults.clear()

    result = {
        "scenario": "serve_swap_kill",
        "requests": report["requests"],
        "ok_requests": report["ok"],
        "shed": report["shed"],
        "errors": report["errors"],
        "p50_ms": report["latency"]["p50_ms"],
        "p99_ms": report["latency"]["p99_ms"],
        "slo_ms": cfg.SERVE_SLO_MS,
        "throughput_rps": report["throughput_rps"],
        "kill_fired": fired == 1,
        "replica_dead": counters.get("serve/replica_dead", 0),
        "replica_refill": counters.get("serve/replica_refill", 0),
        "reloads": counters.get("serve/reloads", 0),
        "reload_refused": counters.get("serve/reload_refused", 0),
        "swapped_step": rm.last_step,
        "refused_steps": sorted(rm.refused),
        "swap_under_load": ("swap_ts" in progress
                            and progress["swap_ts"] <= t_load_end),
        "refused_alert_state": refused_state,
        "pool_generation": table["generation"],
        "pool_ready": table["ready"],
        "new_compilations_under_load": compile_delta,
        "cache_hits": counters.get("serve/cache_hit", 0),
        "wall_s": round(time.time() - t0, 1),
    }
    result["ok"] = (
        report["errors"] == 0
        and report["requests"] == report["ok"] + report["shed"]
        and report["latency"]["p99_ms"] <= cfg.SERVE_SLO_MS
        and result["kill_fired"]
        and result["replica_dead"] == 1
        and result["replica_refill"] == 1
        and result["swapped_step"] == 1
        and table["generation"] == 1
        and result["refused_steps"] == [2]
        and result["swap_under_load"]
        and refused_state == "firing"
        and compile_delta == 0
        and table["ready"] >= replicas)
    return result


SCENARIOS = {
    "kill_resume": scenario_kill_resume,
    "kill_resume_2proc": scenario_kill_resume_2proc,
    "kill_resize": scenario_kill_resize,
    "corrupt_checkpoint": scenario_corrupt_checkpoint,
    "serve_swap_kill": scenario_serve_swap_kill,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="deterministic chaos scenarios over the real "
                    "supervisor + failpoint registry")
    ap.add_argument("scenario", nargs="?", choices=sorted(SCENARIOS),
                    help="which contract to exercise")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and exit")
    ap.add_argument("--out", default=None,
                    help="work dir (default: a fresh temp dir)")
    args = ap.parse_args(argv)

    if args.list or not args.scenario:
        for name, fn in sorted(SCENARIOS.items()):
            print(f"{name}: {' '.join((fn.__doc__ or '').split())}")
        return 0

    out = args.out or tempfile.mkdtemp(prefix=f"chaos_{args.scenario}_")
    os.makedirs(out, exist_ok=True)
    result = SCENARIOS[args.scenario](out)
    print(json.dumps(result, indent=1, default=str))
    return 0 if result.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
