#!/usr/bin/env python3
"""Attack-vs-defense study: does --adv_rename_prob buy robustness?

Trains two matched models on a gen_java_corpus dataset — baseline
(reference training) and defended (--adv_rename_prob, the randomized
rename-augmentation defense from attacks/defense.py) — then attacks
both with the untargeted gradient rename attack (attacks/robustness.py)
and reports clean quality next to attack success rate. Results recorded
in BASELINE.md ("Adversarial robustness" section).

Usage (corpus build is the quality-study recipe):
  python tools/gen_java_corpus.py --out /tmp/rs/raw --names 10000 \
      --methods 100000
  TRAIN_DIR=/tmp/rs/raw/train VAL_DIR=/tmp/rs/raw/val \
      TEST_DIR=/tmp/rs/raw/test DATASET_NAME=rs OUT_DIR=/tmp/rs/ds \
      WORD_VOCAB_SIZE=150000 PATH_VOCAB_SIZE=150000 \
      TARGET_VOCAB_SIZE=60000 ./preprocess.sh
  python tools/robustness_study.py --data /tmp/rs/ds/rs --epochs 6 \
      --n_attacks 300 --adv_prob 0.3
Prints one JSON line per arm and a summary table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_arm(name: str, data: str, epochs: int, batch: int,
            adv_prob: float, n_attacks: int, max_renames: int,
            seed: int, max_contexts: int, detect: bool = False,
            adv_mode: str = "uniform", tag: str = "",
            word_vocab_size: int = 150_000,
            path_vocab_size: int = 150_000,
            target_vocab_size: int = 60_000,
            infeed_chunk: int = 1) -> dict:
    from code2vec_tpu.attacks.robustness import evaluate_robustness
    from code2vec_tpu.config import Config
    from code2vec_tpu.models.jax_model import Code2VecModel

    # the shipped java-large-style config (sampled + bf16 + adafactor)
    cfg = Config(
        MAX_CONTEXTS=max_contexts,
        MAX_TOKEN_VOCAB_SIZE=word_vocab_size,
        MAX_PATH_VOCAB_SIZE=path_vocab_size,
        MAX_TARGET_VOCAB_SIZE=target_vocab_size,
        INFEED_CHUNK=infeed_chunk,
        TRAIN_BATCH_SIZE=batch,
        TEST_BATCH_SIZE=batch,
        NUM_TRAIN_EPOCHS=epochs,
        SAVE_EVERY_EPOCHS=1000,
        NUM_BATCHES_TO_LOG_PROGRESS=200,
        LEARNING_RATE=1e-3,
        SEED=seed,
        USE_SAMPLED_SOFTMAX=True,
        NUM_SAMPLED_CLASSES=4096,
        ADV_RENAME_PROB=adv_prob,
        ADV_RENAME_MODE=adv_mode,
    )
    cfg.train_data_path = data
    cfg.test_data_path = data + ".val.c2v"
    model = Code2VecModel(cfg)
    t0 = time.time()
    model.train()
    train_s = time.time() - t0
    clean = model.evaluate()
    detector = None
    if detect:
        from code2vec_tpu.attacks.detect import RarityDetector
        detector = RarityDetector.from_model(model,
                                             data + ".dict.c2v")
    rob = evaluate_robustness(model, data + ".val.c2v",
                              n_methods=n_attacks,
                              max_renames=max_renames,
                              detector=detector, log=cfg.log)
    row = {
        "arm": name,
        "tag": tag,
        "word_vocab_size": model.vocabs.token_vocab.size,
        "adv_rename_prob": adv_prob,
        "adv_rename_mode": adv_mode if adv_prob > 0 else "-",
        "epochs": epochs,
        "clean_subtoken_f1": round(clean.subtoken_f1, 4),
        "clean_top1": round(clean.topk_acc[0], 4),
        "attack_success_rate": rob["attack_success_rate"],
        "robustness": rob["robustness"],
        "attacked_top1_acc": rob["attacked_top1_acc"],
        "n_attacks": rob["n_methods"],
        "train_seconds": round(train_s, 1),
    }
    for key in ("detection_auc", "detection_tpr_at_5fpr",
                "replacement_token_freq", "original_token_freq"):
        if key in rob:
            row[key] = rob[key]
    print(json.dumps(row), flush=True)
    return row


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", required=True,
                    help="dataset prefix (expects .train/.val .c2v)")
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--adv_prob", type=float, default=0.3)
    ap.add_argument("--adv_mode", default="uniform",
                    choices=["uniform", "batch"],
                    help="defended arm's replacement distribution "
                         "(attacks/defense.py make_rename_augment)")
    ap.add_argument("--n_attacks", type=int, default=300)
    ap.add_argument("--max_renames", type=int, default=1)
    ap.add_argument("--max_contexts", type=int, default=200)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--arms", default="baseline,defended",
                    help="comma list: baseline | defended")
    ap.add_argument("--detect", action="store_true",
                    help="also measure rarity-outlier detection "
                         "(attacks/detect.py) on the attacked methods")
    ap.add_argument("--word_vocab_size", type=int, default=150_000,
                    help="token vocab cap — the detection-regime study "
                         "(deep-tail corpus) needs ~800K so the "
                         "singleton tail stays IN vocab")
    ap.add_argument("--path_vocab_size", type=int, default=150_000)
    ap.add_argument("--target_vocab_size", type=int, default=60_000)
    ap.add_argument("--infeed_chunk", type=int, default=1,
                    help="latency-chunked infeed group size (speeds "
                         "training on the tunneled dev link)")
    ap.add_argument("--tag", default="",
                    help="free-form row label (e.g. the corpus's cue "
                         "redundancy k in the defense grid)")
    ap.add_argument("--out", default=None,
                    help="append JSON rows here too")
    a = ap.parse_args()

    arms = [s.strip() for s in a.arms.split(",")]
    bad = [s for s in arms if s not in ("baseline", "defended")]
    if bad:
        ap.error(f"unknown arm(s) {bad}; valid: baseline, defended")
    rows = []
    for arm in arms:
        prob = 0.0 if arm == "baseline" else a.adv_prob
        row = run_arm(arm, a.data, a.epochs, a.batch, prob,
                      a.n_attacks, a.max_renames, a.seed,
                      a.max_contexts, detect=a.detect,
                      adv_mode=a.adv_mode, tag=a.tag,
                      word_vocab_size=a.word_vocab_size,
                      path_vocab_size=a.path_vocab_size,
                      target_vocab_size=a.target_vocab_size,
                      infeed_chunk=a.infeed_chunk)
        rows.append(row)
        if a.out:
            with open(a.out, "a") as f:
                f.write(json.dumps(row) + "\n")
    print(f"\n{'arm':<10} {'p':>4} {'cleanF1':>8} {'top1':>6} "
          f"{'atk-success':>11} {'atk-top1':>8}")
    for r in rows:
        print(f"{r['arm']:<10} {r['adv_rename_prob']:>4} "
              f"{r['clean_subtoken_f1']:>8} {r['clean_top1']:>6} "
              f"{r['attack_success_rate']:>11} "
              f"{r['attacked_top1_acc']:>8}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
