"""Shared java-large benchmark constants + slope-timing helpers for the
round-4 measurement tools (bench_reconcile.py, xf_profile.py).

bench.py and tools/profile_step.py keep their own self-contained copies
deliberately — bench.py is the driver artifact (run standalone at repo
root every round, must not grow import edges) and profile_step.py is
the round-3 provenance tool; THIS module is the single source for new
tools so shape/methodology fixes stop fanning out (advisor round-4
reuse finding: the bf16-tables fix had to be applied in two places).
"""

from __future__ import annotations

import time

# java-large capacities (SURVEY.md §3 config row) — match bench.py
TOKEN_VOCAB = 1_301_136
PATH_VOCAB = 911_417
TARGET_VOCAB = 261_245
BATCH = 1024
CTX = 200
NUM_SAMPLED = 4096


def slope_time(chain, state, steps: int, warmup: int = 5,
               base: int = 10):
    """Slope timing (BASELINE.md methodology): run chains of `base` and
    `base+steps` calls and difference, cancelling the tunneled
    platform's fixed ~100 ms sync cost. `chain(n, state) -> (seconds,
    state)` must hard-sync via a host transfer of a SCALAR
    (block_until_ready can return early here; transferring a full
    tensor drowns the slope in transfer noise — both failure modes are
    measured, see tools/xf_profile.py round-4 history)."""
    _, state = chain(warmup, state)
    t1, state = chain(base, state)
    t2, state = chain(base + steps, state)
    return (t2 - t1) / steps


def time_fn(fn, args, steps: int, sync=None):
    """Slope-time a stateless `fn(*args)` with a scalar-slice sync."""
    if sync is None:
        def sync(o):
            import jax.numpy as jnp
            return float(jnp.ravel(o)[0])

    def chain(n, _):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = fn(*args)
        sync(out)
        return time.perf_counter() - t0, None

    return slope_time(chain, None, steps)


def load_bench_module():
    """Import repo-root bench.py as a module (it is the standalone
    driver artifact, not a package member). Shared by the tools that
    reuse its measurement entry points (c_sweep_step, int8_profile) so
    the loader does not fan out per tool."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
