class Demo {
    int[] sortedItems;

    boolean contains(int target) {
        int lo = 0;
        int hi = sortedItems.length - 1;
        while (lo <= hi) {
            int mid = (lo + hi) / 2;
            if (sortedItems[mid] == target) {
                return true;
            }
            if (sortedItems[mid] < target) {
                lo = mid + 1;
            } else {
                hi = mid - 1;
            }
        }
        return false;
    }

    int maxValue(int[] values) {
        int best = values[0];
        for (int i = 1; i < values.length; i++) {
            if (values[i] > best) {
                best = values[i];
            }
        }
        return best;
    }
}
