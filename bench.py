#!/usr/bin/env python3
"""Benchmark: training throughput of the java-large config on one chip.

Prints ONE JSON line:
  {"metric": "path-contexts/sec/chip", "value": N, "unit": "...",
   "vs_baseline": N, ...}

Metric (BASELINE.json): path-contexts/sec/chip on java-large =
examples/sec * MAX_CONTEXTS(200), measured over the jitted training step
(sampled softmax over the 261K-name target vocab — the north-star
java-large configuration; full vocab tables at reference capacity),
using the SHIPPED config: bf16 tables, adafactor table optimizer
(training/optimizers.make_optimizer), bf16 compute, Pallas pool on TPU.

Extra keys:
  - hbm_gbps / hbm_ceiling_gbps: achieved HBM bandwidth of the step
    (analytic streaming-traffic model below / measured step time) vs the
    measured 1-GiB-copy streaming ceiling on this chip. The step is
    HBM-bound (BASELINE.md "Phase isolation"), so hbm_gbps close to the
    ceiling means the config is at its roofline and further per-chip
    gains need less *traffic*, not better overlap.
  - transformer_*: the same measurement for --encoder transformer
    (xf_layers=2), the BASELINE.json configs[4] stretch encoder.
  - sparse_*: the carrier-free sparse-update config (ROADMAP item 1:
    --sparse_embeddings, gathered-row diff + dedup/segment-sum +
    live-row row-Adam) with the update phase attributed every round:
    sparse_update_ms (the apply alone, fused Pallas live-row kernel on
    TPU), sparse_update_bytes ([U, E]-aware analytic bytes),
    sparse_update_unique_rows, and sparse_step_floor_pc_per_sec — the
    corrected analytic floor counting [U, E] traffic instead of the
    dense [V, E] carrier.
  - int8_*: the sub-bf16 memory-lever config (ops/quant.py), with the
    requantize phase attributed every round: int8_requant_ms (the
    apply alone, fused Pallas row-pass on TPU), int8_requant_bytes
    (analytic bytes of ONE fused sweep), int8_requant_gbps achieved vs
    int8_requant_floor_ms (= bytes / streaming ceiling — the phase at
    its roofline). int8_hbm_gbps uses the quantized-carrier-aware
    traffic model (bf16 [V, E] grad carrier + int8 q / f32 s r+w).

  - phase_*: the per-phase breakdown of the sparse step (ISSUE 15) —
    the training/phase_probes.py chain slope-timed and differenced
    (embed_gather / concat_dense / forward_pool / backward, table_apply
    as the fused remainder), each with analytic bytes + utilization vs
    the ceiling. tools/bench_regression.py gates every phase's ms
    (LOWER_IS_BETTER) so a single-phase regression cannot hide behind
    a steady headline.

Baseline denominator: derived, methodology-documented single-V100
estimate of the reference step (fp32, full softmax, dense Adam, input
pipeline assumed free — every assumption favoring the reference):
1.94M path-contexts/s, the midpoint of the 1.67M-2.20M device-bound band
computed by tools/v100_roofline.py and anchored against a real TF 2.21
execution of the same graph math by tools/tf_baseline.py. See
BASELINE.md "Baseline denominator". The community-anecdote figure used
in round 1 (700K) survives only as the real-world lower bound.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

V100_BASELINE_PATH_CONTEXTS_PER_SEC = 1_940_000.0  # tools/v100_roofline.py
V100_BASELINE_BAND = (1_675_000.0, 2_197_000.0)

# java-large capacities (SURVEY.md §3 config row)
TOKEN_VOCAB = 1_301_136
PATH_VOCAB = 911_417
TARGET_VOCAB = 261_245
BATCH = 1024
MAX_CONTEXTS = 200
NUM_SAMPLED = 4096
WARMUP_STEPS = 5
MEASURE_STEPS = 40


def _step_hbm_bytes(params, opt_state) -> int:
    """Analytic per-step HBM traffic of the table-dominated phases
    (BASELINE.md "Phase isolation" — the step is streaming-bound on
    exactly this traffic):

      backward: dense grad buffer written once per table (grad dtype ==
                param dtype under value_and_grad);
      optimizer: grads read, params read + written, every optimizer-state
                leaf read + written (Adam: 2 full-table f32 moments;
                adafactor: factored row/col stats, ~V+E per table);
      quantized {q, s} subtrees (tables_dtype int8): the table gradient
                is a bf16 [V, E] CARRIER (ops/quant.py straight-through
                custom_vjp), not an int8 array, so the grad term counts
                2 bytes/elt; the param term is the requantize pass's
                int8 q + f32 s read + write. Sizing the grad by the
                stored dtype undercounted int8 2x (ADVICE r5 finding 2).

    Gathers/activations (~0.3 GB at B=1024, and running at random-access
    bandwidth, not streaming) are excluded — this is a lower bound, so
    achieved GB/s derived from it is conservative."""
    import jax

    from code2vec_tpu.ops.quant import is_quantized

    total = 0
    for p in params.values():
        if is_quantized(p):
            total += p["q"].size * 2 * 2  # bf16 carrier grad write+read
            total += p["q"].size * p["q"].dtype.itemsize * 2  # q r+w
            total += p["s"].size * p["s"].dtype.itemsize * 2  # s r+w
            continue
        # plain leaves — including nested subtrees (transformer "xf")
        for leaf in jax.tree_util.tree_leaves(p):
            b = leaf.size * leaf.dtype.itemsize
            total += b * 4  # grad write + grad read + param read + write
    for s in jax.tree_util.tree_leaves(opt_state):
        total += s.size * s.dtype.itemsize * 2  # state read + write
    return total


def _measure_hbm_ceiling() -> float:
    """Streaming bandwidth ceiling (ops/membench.py — shared with
    tools/profile_step.py)."""
    from code2vec_tpu.ops.membench import measure_hbm_ceiling
    return measure_hbm_ceiling()


def _java_large_dims(encoder_type: str = "bag",
                     tables_dtype: str = "bfloat16",
                     max_contexts: int = MAX_CONTEXTS):
    from code2vec_tpu.models.encoder import ModelDims
    # xf_heads=3: the shipped default (head_dim 128 = MXU lane width;
    # quality-identical to 4 heads, 9% faster — BASELINE.md round 4)
    return ModelDims(token_vocab_size=TOKEN_VOCAB,
                     path_vocab_size=PATH_VOCAB,
                     target_vocab_size=TARGET_VOCAB,
                     embeddings_size=128, max_contexts=max_contexts,
                     tables_dtype=tables_dtype, encoder_type=encoder_type,
                     xf_layers=2, xf_heads=3)


def _device_batches(n: int = 4, max_contexts: int = MAX_CONTEXTS):
    """n distinct uniform-random batches, placed on device once (the
    rotation defeats any cross-step input caching; ids are uniform —
    the worst case for the embedding gathers)."""
    import jax.numpy as jnp

    r = np.random.default_rng(0)
    out = []
    for _ in range(n):
        arrays = (
            r.integers(0, TARGET_VOCAB, size=(BATCH,), dtype=np.int32),
            r.integers(0, TOKEN_VOCAB, size=(BATCH, max_contexts),
                       dtype=np.int32),
            r.integers(0, PATH_VOCAB, size=(BATCH, max_contexts),
                       dtype=np.int32),
            r.integers(0, TOKEN_VOCAB, size=(BATCH, max_contexts),
                       dtype=np.int32),
            np.ones((BATCH, max_contexts), dtype=np.float32),
            np.ones((BATCH,), dtype=np.float32))
        out.append(tuple(jnp.asarray(a) for a in arrays))
    return out


def _slope_time(chain, state):
    """Slope timing: two chain lengths, differenced — cancels the fixed
    ~100 ms dispatch/sync overhead of the tunneled platform. `chain(n,
    state) -> (seconds, state)` must hard-sync via a host transfer
    (block_until_ready can return early on this platform)."""
    _, state = chain(WARMUP_STEPS, state)
    t1, state = chain(10, state)
    t2, state = chain(10 + MEASURE_STEPS, state)
    return (t2 - t1) / MEASURE_STEPS


def _measure_fwd_bwd_floor():
    """Forward+backward only (no optimizer), with the IDENTICAL math and
    inputs as the full step (dropout on, same 4-batch rotation): the
    zero-cost-optimizer ceiling of this config. The full step can't beat
    B*C/floor_dt pc/s whatever the optimizer does — the floor is the
    backward scatter-add of the dense embedding grads running at
    random-access (not streaming) bandwidth; see BASELINE.md round-3
    phase floors."""
    import jax
    import jax.numpy as jnp

    from code2vec_tpu.models.encoder import init_params
    from code2vec_tpu.training.steps import make_train_loss_fn

    dims = _java_large_dims()
    params = init_params(jax.random.PRNGKey(0), dims)
    batches = _device_batches()
    # the exact loss make_train_step differentiates — shared builder
    loss_fn = make_train_loss_fn(
        dims, use_sampled_softmax=True, num_sampled=NUM_SAMPLED,
        compute_dtype=jnp.bfloat16,
        use_pallas=jax.default_backend() == "tpu")
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    def chain(n, rng):
        # keys pre-split OUTSIDE the timed region: each jax.random.split
        # is its own dispatch, and on the tunneled platform dispatches
        # cost ~2 ms each — splitting in the loop would double the
        # per-step dispatch overhead the slope can't cancel.
        rng, sub = jax.random.split(rng)
        keys = list(jax.random.split(sub, max(n, 1)))
        t0 = time.perf_counter()
        for i in range(n):
            loss, _g = grad_fn(params, batches[i % len(batches)],
                               keys[i])
        float(loss)
        return time.perf_counter() - t0, rng

    dt = _slope_time(chain, jax.random.PRNGKey(3))
    return BATCH * MAX_CONTEXTS / dt


def _measure_sparse_update_phase():
    """Slope-time the sparse table-update apply ALONE (dedup +
    segment-sum + live-row row-Adam over the three tables — the
    training/sparse_update facade exactly as the sparse train step runs
    it: fused Pallas live-row kernel on TPU, XLA reference elsewhere)
    plus the analytic [U, E] bytes one apply must move, so the phase is
    attributed against the streaming ceiling every round. Returns
    (ms, bytes, unique_rows, fused?)."""
    import functools

    import jax
    import jax.numpy as jnp

    from code2vec_tpu.models.encoder import init_params
    from code2vec_tpu.training.sparse_adam import init_row_adam
    from code2vec_tpu.training.sparse_update import \
        sparse_update_traffic_bytes

    dims = _java_large_dims()
    params = init_params(jax.random.PRNGKey(0), dims)
    batch = _device_batches(1)[0]
    labels, src, pth, dst, _mask, _w = batch
    fused = jax.default_backend() == "tpu"

    # the exact id/cotangent layout the sparse step feeds the facade
    # (target rows are code-vector-wide, not E-wide)
    r = np.random.default_rng(5)
    sampled = jnp.asarray(
        r.integers(0, TARGET_VOCAB, NUM_SAMPLED), jnp.int32)
    table_ids = {
        "token_emb": jnp.concatenate([src.reshape(-1),
                                      dst.reshape(-1)]),
        "path_emb": pth.reshape(-1),
        "target_emb": jnp.concatenate([labels, sampled]),
    }
    grads = {k: jnp.asarray(
        r.normal(size=(int(v.size), params[k].shape[-1])) * 1e-3,
        jnp.bfloat16)
        for k, v in table_ids.items()}
    tables = {k: params[k] for k in table_ids}
    states = {k: init_row_adam(params[k]) for k in table_ids}

    unique_rows = {k: int(np.unique(np.asarray(v)).size)
                   for k, v in table_ids.items()}
    nbytes = sum(
        sparse_update_traffic_bytes(tables[k], int(v.size),
                                    unique_rows[k], grad_itemsize=2)
        for k, v in table_ids.items())

    from code2vec_tpu.training.sparse_update import sparse_row_adam

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def apply(tables, states, count):
        new_t, new_s = {}, {}
        for k in sorted(tables):
            new_t[k], new_s[k] = sparse_row_adam(
                tables[k], states[k], table_ids[k], grads[k],
                count=count, lr=1e-3, fused=fused)
        return new_t, new_s, count + 1

    def chain(n, state):
        tables, states, count = state
        t0 = time.perf_counter()
        for _ in range(n):
            tables, states, count = apply(tables, states, count)
        # hard sync via a scalar host transfer (slope-timing contract)
        float(tables["path_emb"].ravel()[0])
        return time.perf_counter() - t0, (tables, states, count)

    dt = max(_slope_time(chain, (tables, states,
                                 jnp.asarray(1, jnp.int32))), 1e-9)
    return dt * 1e3, nbytes, sum(unique_rows.values()), fused


def _measure_sparse_step():
    """The full sparse-update train step (make_train_step's sparse
    dispatch — gathered-row diff + dedup/segment-sum/live-row apply,
    bf16 tables, row-Adam): the config ROADMAP item 1 aims at the old
    8.48M fwd/bwd floor with. Returns (pc/s, ms, hbm_gbps,
    floor_bytes) — hbm_gbps uses the [U, E]-aware analytic traffic
    model (sparse_update.sparse_step_floor_bytes), NOT the dense
    _step_hbm_bytes, whose [V, E] carrier + full-table walk this step
    does not perform; the caller derives the corrected floor from
    floor_bytes over the measured ceiling."""
    import jax
    import jax.numpy as jnp
    import optax

    from code2vec_tpu.models.encoder import init_params
    from code2vec_tpu.training.sparse_steps import init_sparse_opt_state
    from code2vec_tpu.training.sparse_update import \
        sparse_step_floor_bytes
    from code2vec_tpu.training.steps import make_train_step

    dims = _java_large_dims()
    params = init_params(jax.random.PRNGKey(0), dims)
    dense_opt = optax.adam(1e-3)
    opt_state = init_sparse_opt_state(params, dense_opt, True)
    step = make_train_step(dims, dense_opt, use_sampled_softmax=True,
                           num_sampled=NUM_SAMPLED,
                           compute_dtype=jnp.bfloat16,
                           use_pallas=jax.default_backend() == "tpu",
                           sparse_updates=True, learning_rate=1e-3)
    floor_bytes = sparse_step_floor_bytes(params, BATCH, MAX_CONTEXTS,
                                          num_sampled=NUM_SAMPLED)
    batches = _device_batches()

    def chain(n, state):
        params, opt_state, rng = state
        rng, sub = jax.random.split(rng)
        keys = list(jax.random.split(sub, max(n, 1)))
        t0 = time.perf_counter()
        for i in range(n):
            params, opt_state, loss = step(params, opt_state,
                                           batches[i % len(batches)],
                                           keys[i])
        float(loss)
        return time.perf_counter() - t0, (params, opt_state, rng)

    dt = _slope_time(chain, (params, opt_state, jax.random.PRNGKey(2)))
    return (BATCH * MAX_CONTEXTS / dt, dt * 1e3,
            floor_bytes / dt / 1e9, floor_bytes)


def _measure_phase_breakdown(sparse_step_ms: float, ceiling: float):
    """Slope-time the sparse-config probe chain
    (training/phase_probes.py — the SAME cumulative prefixes the
    in-train sampler dispatches, so the bench breakdown and the live
    `train/phase/*` timers can never measure different math) and
    difference it into the per-phase attribution (ISSUE 15):
    embed_gather / concat_dense / forward_pool / backward, with
    table_apply = the measured full sparse step minus the chain tail
    (the fused remainder — the sampled path's rule). Each phase also
    reports its analytic bytes (sparse_update.phase_traffic_bytes) and
    utilization vs the streaming ceiling, so tools/bench_regression.py
    can gate each phase's ms (LOWER_IS_BETTER) instead of only the
    headline pc/s. Returns the `phase_*` result keys."""
    import jax
    import jax.numpy as jnp

    from code2vec_tpu.models.encoder import init_params
    from code2vec_tpu.obs.phases import derive_chain_phases
    from code2vec_tpu.training.phase_probes import make_code2vec_probes
    from code2vec_tpu.training.sparse_update import phase_traffic_bytes

    dims = _java_large_dims()
    params = init_params(jax.random.PRNGKey(0), dims)
    kit = make_code2vec_probes(dims, None, use_sampled_softmax=True,
                               num_sampled=NUM_SAMPLED,
                               compute_dtype=jnp.bfloat16,
                               sparse_updates=True)
    batches = _device_batches()
    names, cum = [], []
    for name, fn in kit.chain:
        def chain(n, rng, fn=fn):
            rng, sub = jax.random.split(rng)
            keys = list(jax.random.split(sub, max(n, 1)))
            out = None
            t0 = time.perf_counter()
            for i in range(n):
                out = fn(params, batches[i % len(batches)], keys[i])
            # hard sync via a scalar host transfer (slope contract;
            # ravel handles the forward probe's 0-d loss)
            leaf = jax.tree_util.tree_leaves(out)[0]
            float(jnp.sum(leaf.ravel()[:1].astype(jnp.float32)))
            return time.perf_counter() - t0, rng

        dt = max(_slope_time(chain, jax.random.PRNGKey(11)), 0.0)
        names.append(name)
        cum.append(dt * 1e3)
    phases = dict(derive_chain_phases(names, cum))
    phases["table_apply"] = max(0.0, sparse_step_ms - cum[-1])
    nbytes = phase_traffic_bytes(params, BATCH, MAX_CONTEXTS,
                                 num_sampled=NUM_SAMPLED, sparse=True)
    out = {}
    for name, ms in phases.items():
        out[f"phase_{name}_ms"] = round(ms, 3)
        nb = nbytes.get(name)
        if nb:
            out[f"phase_{name}_bytes"] = int(nb)
            if ms > 0:
                gbps = nb / (ms / 1e3) / 1e9
                out[f"phase_{name}_vs_ceiling"] = round(
                    gbps / (ceiling / 1e9), 3)
    out["phase_sum_ms"] = round(cum[-1] + phases["table_apply"], 3)
    return out


def _measure_requant_phase():
    """Slope-time the int8 requantize apply ALONE over the two
    quantized tables (the fused Pallas row-pass on TPU, the XLA
    reference elsewhere — ops/quant.requantize's auto-select, i.e. the
    exact code the train step runs) plus the analytic bytes one fused
    sweep must move, so the phase is attributed against the streaming
    ceiling every round instead of once per profiling session
    (VERDICT r5 weak #2). Returns (ms, bytes, fused?)."""
    import functools

    import jax
    import jax.numpy as jnp

    from code2vec_tpu.models.encoder import init_params
    from code2vec_tpu.ops.pallas_requant import requant_traffic_bytes
    from code2vec_tpu.ops.quant import is_quantized, requantize

    dims = _java_large_dims("bag", tables_dtype="int8")
    params = init_params(jax.random.PRNGKey(0), dims)
    qkeys = sorted(k for k in params if is_quantized(params[k]))
    # the optimizer's table output is a bf16 [V, E] update (carrier
    # grads are bf16); a fixed sub-quantum magnitude keeps q stable
    updates = {k: jnp.full(params[k]["q"].shape, 1e-5, jnp.bfloat16)
               for k in qkeys}
    nbytes = sum(requant_traffic_bytes(params[k], updates[k])
                 for k in qkeys)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def apply(tables, rng):
        rng, *qrngs = jax.random.split(rng, 1 + len(qkeys))
        new = {k: requantize(tables[k], updates[k], r)
               for k, r in zip(qkeys, qrngs)}
        return new, rng

    def chain(n, state):
        tables, rng = state
        t0 = time.perf_counter()
        for _ in range(n):
            tables, rng = apply(tables, rng)
        # hard sync via a scalar host transfer (slope-timing contract)
        float(tables[qkeys[0]]["s"].ravel()[0])
        return time.perf_counter() - t0, (tables, rng)

    tables0 = {k: params[k] for k in qkeys}
    dt = max(_slope_time(chain, (tables0, jax.random.PRNGKey(7))), 1e-9)
    return dt * 1e3, nbytes, jax.default_backend() == "tpu"


def _measure_encoder(encoder_type: str, tables_dtype: str = "bfloat16",
                     max_contexts: int = MAX_CONTEXTS):
    """Build the shipped train step for one encoder and time it.
    Returns (path_contexts_per_sec, ms_per_step, hbm_gbps)."""
    import jax
    import jax.numpy as jnp

    from code2vec_tpu.models.encoder import init_params
    from code2vec_tpu.ops.quant import opt_param_view
    from code2vec_tpu.training.optimizers import make_optimizer
    from code2vec_tpu.training.steps import make_train_step

    dims = _java_large_dims(encoder_type, tables_dtype, max_contexts)
    params = init_params(jax.random.PRNGKey(0), dims)
    optimizer = make_optimizer(1e-3)  # shipped default: adafactor tables
    # int8 tables: the optimizer sees the flat [V, E] view (shared
    # helper so the structure can't drift from the model's)
    opt_state = optimizer.init(opt_param_view(params))
    hbm_bytes = _step_hbm_bytes(params, opt_state)
    step = make_train_step(dims, optimizer, use_sampled_softmax=True,
                           num_sampled=NUM_SAMPLED,
                           compute_dtype=jnp.bfloat16,
                           use_pallas=jax.default_backend() == "tpu")
    batches = _device_batches(max_contexts=max_contexts)

    def chain(n, state):
        """Run n chained steps; the donated-params chain serializes
        them, so the final host transfer bounds the full computation.
        RNG keys are pre-split outside the timed region (a split per
        step would add a second ~2 ms dispatch per iteration on the
        tunneled platform — overhead the slope cannot cancel)."""
        params, opt_state, rng = state
        rng, sub = jax.random.split(rng)
        keys = list(jax.random.split(sub, max(n, 1)))
        t0 = time.perf_counter()
        for i in range(n):
            params, opt_state, loss = step(params, opt_state,
                                           batches[i % len(batches)],
                                           keys[i])
        float(loss)
        return time.perf_counter() - t0, (params, opt_state, rng)

    dt = _slope_time(chain, (params, opt_state, jax.random.PRNGKey(1)))
    pc_per_sec = BATCH * max_contexts / dt
    return pc_per_sec, dt * 1e3, hbm_bytes / dt / 1e9


def main(argv=None) -> None:
    # argv=None (programmatic / test callers) means "no flags", NOT
    # sys.argv — the CLI entry below passes sys.argv[1:] explicitly.
    ap = argparse.ArgumentParser(description="one-chip java-large "
                                             "throughput benchmark")
    ap.add_argument("--telemetry_dir", default=None,
                    help="also emit the measurements as telemetry "
                         "events (code2vec_tpu/obs): BENCH rounds and "
                         "train runs share one JSONL format")
    ap.add_argument("--metrics_port", type=int, default=0,
                    help="serve /metrics //healthz //vars while the "
                         "benchmark runs (phase results appear as "
                         "bench/* gauges the moment each phase "
                         "lands); 0 = off")
    args = ap.parse_args(argv if argv is not None else [])
    from code2vec_tpu.obs import MetricsServer, Telemetry
    if args.telemetry_dir:
        tele = Telemetry.create(args.telemetry_dir, component="bench")
    elif args.metrics_port:
        # live scrape without persistence: the registry lives in
        # memory, /metrics serves it
        tele = Telemetry.memory("bench")
    else:
        tele = Telemetry.disabled()
    metrics_server = MetricsServer.create(
        tele.make_threadsafe() if tele.enabled else tele,
        port=args.metrics_port)
    metrics_server.start()

    def _live(**kv) -> None:
        # publish each phase's numbers the moment they land, so a
        # scraper watching --metrics_port sees progress mid-benchmark
        # (static: phase results are set-once facts, not heartbeats —
        # they must not read as stale while later phases run)
        for k, v in kv.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                tele.gauge(f"bench/{k}", v, emit=False, static=True)

    ceiling = _measure_hbm_ceiling()
    _live(hbm_ceiling_gbps=ceiling / 1e9, phases_done=1)
    value, ms, hbm_gbps = _measure_encoder("bag")
    _live(value=value, ms_per_step=ms, hbm_gbps=hbm_gbps,
          phases_done=2)
    floor = _measure_fwd_bwd_floor()
    _live(fwd_bwd_floor_pc_per_sec=floor, phases_done=3)
    i8_value, i8_ms, i8_hbm = _measure_encoder("bag", tables_dtype="int8")
    _live(int8_pc_per_sec=i8_value, int8_ms_per_step=i8_ms,
          phases_done=4)
    rq_ms, rq_bytes, rq_fused = _measure_requant_phase()
    rq_gbps = rq_bytes / (rq_ms / 1e3) / 1e9
    _live(int8_requant_ms=rq_ms, phases_done=5)
    sp_value, sp_ms, sp_hbm, sp_floor_bytes = _measure_sparse_step()
    sp_floor = BATCH * MAX_CONTEXTS / (sp_floor_bytes / ceiling)
    _live(sparse_pc_per_sec=sp_value, sparse_ms_per_step=sp_ms,
          phases_done=6)
    su_ms, su_bytes, su_rows, su_fused = _measure_sparse_update_phase()
    su_gbps = su_bytes / (su_ms / 1e3) / 1e9
    _live(sparse_update_ms=su_ms, phases_done=7)
    # per-phase breakdown of the sparse step (ISSUE 15): the full
    # attribution table every round, so bench_regression gates each
    # phase's ms instead of only the headline pc/s
    phase_keys = _measure_phase_breakdown(sp_ms, ceiling)
    _live(phases_done=8, **phase_keys)
    xf_value, xf_ms, xf_hbm = _measure_encoder("transformer")
    _live(transformer_pc_per_sec=xf_value,
          transformer_ms_per_step=xf_ms, phases_done=9)
    result = {
        "metric": "path-contexts/sec/chip",
        "value": round(value, 1),
        "unit": "path-contexts/sec/chip (java-large, sampled softmax, "
                "batch 1024, bf16 compute + bf16 tables, adafactor "
                "tables)",
        "vs_baseline": round(value / V100_BASELINE_PATH_CONTEXTS_PER_SEC,
                             3),
        "baseline_denominator": V100_BASELINE_PATH_CONTEXTS_PER_SEC,
        "baseline_band": V100_BASELINE_BAND,
        "baseline_methodology": "measured-anchored V100 estimate "
                                "(tools/v100_roofline.py + "
                                "tools/tf_baseline.py; BASELINE.md)",
        "vs_baseline_band": [
            round(value / V100_BASELINE_BAND[1], 3),
            round(value / V100_BASELINE_BAND[0], 3)],
        "ms_per_step": round(ms, 2),
        "hbm_gbps": round(hbm_gbps, 1),
        "hbm_ceiling_gbps": round(ceiling / 1e9, 1),
        "hbm_utilization": round(hbm_gbps / (ceiling / 1e9), 3),
        # zero-cost-optimizer ceiling of this config (fwd+bwd only):
        # the step is backward-scatter-bound, so value/floor close to 1
        # means the optimizer is no longer the lever (BASELINE.md)
        "fwd_bwd_floor_pc_per_sec": round(floor, 1),
        "optimizer_efficiency": round(value / floor, 3),
        # sub-bf16 lever (ops/quant.py): int8 token/path tables +
        # per-row scales, stochastic-rounding requantize
        "int8_pc_per_sec": round(i8_value, 1),
        "int8_ms_per_step": round(i8_ms, 2),
        "int8_vs_baseline": round(
            i8_value / V100_BASELINE_PATH_CONTEXTS_PER_SEC, 3),
        # int8 analytic-traffic bandwidth (quantized-carrier-aware
        # _step_hbm_bytes) + the requantize phase attributed against
        # the streaming ceiling: requant_ms at the floor (_floor_ms =
        # one fused sweep's bytes / ceiling) means the memory lever is
        # speed-neutral; the round-5 unfused phase ran ~9.7 ms
        "int8_hbm_gbps": round(i8_hbm, 1),
        "int8_requant_ms": round(rq_ms, 3),
        "int8_requant_bytes": int(rq_bytes),
        "int8_requant_gbps": round(rq_gbps, 1),
        "int8_requant_floor_ms": round(rq_bytes / ceiling * 1e3, 3),
        "int8_requant_vs_ceiling": round(rq_gbps / (ceiling / 1e9), 3),
        "int8_requant_fused": rq_fused,
        # sparse table-update lever (ROADMAP item 1, round 13): the
        # carrier-free step (--sparse_embeddings, bf16 tables,
        # row-Adam) + the dedup/segment-sum/live-row phase attributed
        # alone. sparse_step_floor_pc_per_sec is the CORRECTED analytic
        # floor counting [U, E] traffic (sparse_update.
        # sparse_step_floor_bytes) instead of the dense [V, E] carrier
        # + full-table walk; the acceptance story is sparse_pc_per_sec
        # punching through the old measured fwd_bwd floor above while
        # sparse_optimizer_efficiency (vs that OLD floor) exceeds 0.9.
        "sparse_pc_per_sec": round(sp_value, 1),
        "sparse_ms_per_step": round(sp_ms, 2),
        "sparse_hbm_gbps": round(sp_hbm, 1),
        "sparse_vs_baseline": round(
            sp_value / V100_BASELINE_PATH_CONTEXTS_PER_SEC, 3),
        "sparse_step_floor_pc_per_sec": round(sp_floor, 1),
        "sparse_optimizer_efficiency": round(sp_value / floor, 3),
        "sparse_update_ms": round(su_ms, 3),
        "sparse_update_bytes": int(su_bytes),
        "sparse_update_gbps": round(su_gbps, 1),
        "sparse_update_floor_ms": round(su_bytes / ceiling * 1e3, 3),
        "sparse_update_vs_ceiling": round(
            su_gbps / (ceiling / 1e9), 3),
        "sparse_update_unique_rows": int(su_rows),
        "sparse_update_fused": su_fused,
        # per-phase breakdown of the sparse step (ISSUE 15): the
        # slope-timed probe chain (training/phase_probes.py — the same
        # prefixes --phase_profile samples in-train) differenced into
        # embed_gather / concat_dense / forward_pool / backward ms,
        # table_apply as the fused remainder, each with its analytic
        # bytes + utilization vs the streaming ceiling. Gated
        # LOWER_IS_BETTER by tools/bench_regression.py so a single
        # phase regressing hides behind neither the headline nor
        # another phase's win.
        **phase_keys,
        "transformer_pc_per_sec": round(xf_value, 1),
        "transformer_ms_per_step": round(xf_ms, 2),
        "transformer_hbm_gbps": round(xf_hbm, 1),
        "transformer_vs_baseline": round(
            xf_value / V100_BASELINE_PATH_CONTEXTS_PER_SEC, 3),
    }
    if tele.enabled:
        tele.event("bench", **result)
        for k, v in result.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                tele.gauge(f"bench/{k}", v, emit=False)
    metrics_server.stop()
    if tele.enabled:
        tele.close()
    print(json.dumps(result))


if __name__ == "__main__":
    main(sys.argv[1:])
