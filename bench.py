#!/usr/bin/env python3
"""Benchmark: training throughput of the java-large config on one chip.

Prints ONE JSON line:
  {"metric": "path-contexts/sec/chip", "value": N, "unit": "...",
   "vs_baseline": N}

Metric (BASELINE.json): path-contexts/sec/chip on java-large =
examples/sec * MAX_CONTEXTS(200), measured over the jitted training step
(sampled softmax over the 261K-name target vocab — the north-star
java-large configuration; full vocab tables at reference capacity).

Baseline denominator: derived, methodology-documented single-V100
estimate of the reference step (fp32, full softmax, dense Adam, input
pipeline assumed free — every assumption favoring the reference):
1.94M path-contexts/s, the midpoint of the 1.67M-2.20M device-bound band
computed by tools/v100_roofline.py and anchored against a real TF 2.21
execution of the same graph math by tools/tf_baseline.py. See
BASELINE.md "Baseline denominator". The community-anecdote figure used
in round 1 (700K) survives only as the real-world lower bound.
"""

from __future__ import annotations

import json
import time

import numpy as np

V100_BASELINE_PATH_CONTEXTS_PER_SEC = 1_940_000.0  # tools/v100_roofline.py
V100_BASELINE_BAND = (1_675_000.0, 2_197_000.0)

# java-large capacities (SURVEY.md §3 config row)
TOKEN_VOCAB = 1_301_136
PATH_VOCAB = 911_417
TARGET_VOCAB = 261_245
BATCH = 1024
MAX_CONTEXTS = 200
NUM_SAMPLED = 4096
WARMUP_STEPS = 5
MEASURE_STEPS = 40


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from code2vec_tpu.models.encoder import ModelDims, init_params
    from code2vec_tpu.training.steps import make_train_step

    # the shipped default config (config.py): bf16 tables (quality-
    # validated in BASELINE.md's 50K-vocab study), bf16 compute, Pallas
    # pool on TPU, sampled softmax, dense Adam
    dims = ModelDims(token_vocab_size=TOKEN_VOCAB,
                     path_vocab_size=PATH_VOCAB,
                     target_vocab_size=TARGET_VOCAB,
                     embeddings_size=128, max_contexts=MAX_CONTEXTS,
                     tables_dtype="bfloat16")
    params = init_params(jax.random.PRNGKey(0), dims)
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)
    step = make_train_step(dims, optimizer, use_sampled_softmax=True,
                           num_sampled=NUM_SAMPLED,
                           compute_dtype=jnp.bfloat16,
                           use_pallas=jax.default_backend() == "tpu")

    r = np.random.default_rng(0)
    def batch_for(i):
        labels = r.integers(0, TARGET_VOCAB, size=(BATCH,), dtype=np.int32)
        src = r.integers(0, TOKEN_VOCAB, size=(BATCH, MAX_CONTEXTS),
                         dtype=np.int32)
        pth = r.integers(0, PATH_VOCAB, size=(BATCH, MAX_CONTEXTS),
                         dtype=np.int32)
        dst = r.integers(0, TOKEN_VOCAB, size=(BATCH, MAX_CONTEXTS),
                         dtype=np.int32)
        mask = np.ones((BATCH, MAX_CONTEXTS), dtype=np.float32)
        weights = np.ones((BATCH,), dtype=np.float32)
        return tuple(jnp.asarray(a) for a in
                     (labels, src, pth, dst, mask, weights))

    rng = jax.random.PRNGKey(1)
    # a few distinct host batches so we're not timing a cached input
    batches = [batch_for(i) for i in range(4)]
    for i in range(WARMUP_STEPS):
        rng, k = jax.random.split(rng)
        params, opt_state, loss = step(params, opt_state,
                                       batches[i % len(batches)], k)
    float(loss)  # hard sync; block_until_ready can return early on the
    # tunneled axon platform, so sync via a host transfer instead

    t0 = time.perf_counter()
    for i in range(MEASURE_STEPS):
        rng, k = jax.random.split(rng)
        params, opt_state, loss = step(params, opt_state,
                                       batches[i % len(batches)], k)
    # single hard sync at the end: the donated-params chain serializes all
    # MEASURE_STEPS steps, so this bounds the full computation
    float(loss)
    dt = time.perf_counter() - t0

    examples_per_sec = MEASURE_STEPS * BATCH / dt
    value = examples_per_sec * MAX_CONTEXTS
    print(json.dumps({
        "metric": "path-contexts/sec/chip",
        "value": round(value, 1),
        "unit": "path-contexts/sec/chip (java-large, sampled softmax, "
                "batch 1024, bf16 compute + bf16 tables)",
        "vs_baseline": round(value / V100_BASELINE_PATH_CONTEXTS_PER_SEC,
                             3),
        "baseline_denominator": V100_BASELINE_PATH_CONTEXTS_PER_SEC,
        "baseline_band": V100_BASELINE_BAND,
        "baseline_methodology": "measured-anchored V100 estimate "
                                "(tools/v100_roofline.py + "
                                "tools/tf_baseline.py; BASELINE.md)",
        "vs_baseline_band": [
            round(value / V100_BASELINE_BAND[1], 3),
            round(value / V100_BASELINE_BAND[0], 3)],
    }))


if __name__ == "__main__":
    main()
