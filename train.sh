#!/usr/bin/env bash
# Train driver — reference-compatible (SURVEY.md §3 "Train driver"):
# set the dataset name/paths, invoke code2vec.py. Runs unchanged on the
# TPU backend.
set -euo pipefail

type=${type:-java-small}
dataset_name=${dataset_name:-${type}}
data_dir=${data_dir:-data}
data=${data_dir}/${dataset_name}/${dataset_name}
test_data=${data_dir}/${dataset_name}/${dataset_name}.val.c2v
model_dir=${model_dir:-models/${dataset_name}}

mkdir -p "${model_dir}"
set -x
python3 code2vec.py --data "${data}" --test "${test_data}" \
  --save "${model_dir}/saved_model" --backend "${backend:-tpu}" "$@"
